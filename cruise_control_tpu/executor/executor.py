"""Executor — applies optimization proposals to the cluster.

Reference: executor/Executor.java:72 — executeProposals():395,
ProposalExecutionRunnable.run():749 (phase 1 inter/intra-broker moves,
phase 2 leadership), updateOngoingExecutionState():912 (progress loop),
maybeReexecuteTasks():1430, graceful + forced stop (:1145 deletes the ZK
reassignment node; here admin.cancel_reassignments), per-broker
concurrency caps (Executor.java:485-510), removed/demoted broker history.

The execution loop is tick-driven: each `progress_check` round collects
finished reassignments from the ClusterAdmin, transitions tasks, and
drains new ones within concurrency caps.  `execute_proposals` runs the
loop synchronously (simulation advances via admin.tick) or in a
background thread against a real cluster.

Crash safety (this file + executor/journal.py): when a journal is
attached, every execution start, task transition, throttle change and
reservation change is durably recorded.  A fresh Executor replays the
journal on construction; an execution the predecessor left in flight puts
the executor in RECOVERING state — reservations restored, leaked
throttles swept, every journaled task reconciled against the live
topology (landed -> COMPLETED, still moving -> re-adopted, vanished ->
re-submitted or DEAD) — and `resume_recovered_execution()` drives the
remainder to completion with zero duplicate submissions.

Two in-loop guardians (reference ConcurrencyAdjuster + stuck-task
handling): the stuck-move reaper cancels reassignments whose progress
watermark stalls past `executor.reaper.stuck.timeout.s` (rollback via
per-partition cancellation where the controller supports it, else DEAD)
and raises an EXECUTION_STUCK anomaly; the ConcurrencyAdjuster samples
cluster stress (under-replicated partitions, task throughput) every tick
and AIMD-adjusts the movement caps between `executor.adaptive.{min,max}`.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.admin import ClusterAdmin, LeadershipSpec, ReassignmentSpec
from cruise_control_tpu.executor.journal import (
    ExecutionJournal,
    task_to_journal,
)
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import ReplicaMovementStrategy
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskTracker,
    TaskState,
    TaskType,
)
from cruise_control_tpu.executor.throttle import ReplicationThrottleHelper

_TERMINAL = (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD)


class ExecutorState(enum.Enum):
    """Reference executor/ExecutorState.java states (+ RECOVERING: journal
    replay reconciled an execution a crashed predecessor left in flight
    and the remainder has not resumed/finished yet)."""

    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    RECOVERING = "RECOVERING"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = (
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    )
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


@dataclasses.dataclass
class ExecutionOptions:
    """Concurrency caps (reference config/constants/ExecutorConfig.java:
    num.concurrent.partition.movements.per.broker default 5,
    num.concurrent.intra.broker.partition.movements default 2,
    num.concurrent.leader.movements default 1000)."""

    concurrent_partition_movements_per_broker: int = 5
    concurrent_intra_broker_partition_movements: int = 2
    concurrent_leader_movements: int = 1000
    #: global cap on concurrently ongoing movements cluster-wide, on top of
    #: the per-broker caps (reference ExecutorConfig
    #: max.num.cluster.movements, default 1250)
    max_num_cluster_movements: int = 1250
    #: a leadership move the topology has not confirmed within this window
    #: is declared DEAD (reference ExecutorConfig leader.movement.timeout.ms)
    leader_movement_timeout_s: float = 180.0
    #: MB/s floors for the slow-task alert: a replica move alerts when its
    #: execution time exceeds task_execution_alerting_s AND its data rate is
    #: below this (reference ExecutorConfig
    #: {inter,intra}.broker.replica.movement.rate.alerting.threshold)
    inter_broker_rate_alerting_mb_s: float = 0.1
    intra_broker_rate_alerting_mb_s: float = 0.2
    replication_throttle_bytes_per_s: float | None = None
    progress_check_interval_s: float = 0.5
    #: tasks in progress longer than this raise an alert flag
    task_execution_alerting_s: float = 90.0
    #: times a reassignment the controller dropped (vanished from the
    #: in-progress set without landing) is re-submitted before the task is
    #: declared DEAD.  The reference re-executes unboundedly
    #: (Executor.maybeReexecuteTasks:1430); the bound here exists so a
    #: pathologically dropping controller cannot loop forever, and defaults
    #: HIGH because the landed-check reads topology metadata that can lag
    #: the controller on a real cluster (a completed move that looks
    #: unplaced for a few ticks must not be DEAD-marked — 64 ticks at the
    #: 0.5s default interval tolerates ~30s of metadata staleness)
    max_reexecution_attempts: int = 64
    #: consecutive ticks a finished-looking logdir copy may stay
    #: UNVERIFIABLE (unreachable broker) before its task is declared DEAD
    max_intra_verify_failures: int = 8
    max_ticks: int = 10_000  # simulation safety bound
    #: stuck-move reaper (executor.reaper.stuck.timeout.s): an inter-broker
    #: move whose progress watermark (remaining bytes, when the admin can
    #: report them, else any completion) has not advanced for this long is
    #: cancelled — rolled back to the original replica set where the
    #: controller supports per-partition cancellation, DEAD otherwise —
    #: and an EXECUTION_STUCK anomaly is raised.  None disables.
    reaper_stuck_timeout_s: float | None = None
    #: load-aware adaptive concurrency (reference ConcurrencyAdjuster):
    #: AIMD on the per-broker + cluster-wide movement caps, driven by
    #: under-replicated partitions and task throughput
    adaptive_enabled: bool = False
    adaptive_min_concurrency: int = 1
    adaptive_max_concurrency: int = 64
    adaptive_backoff_factor: float = 0.5
    adaptive_recover_step: int = 1
    #: URPs above the execution-start baseline tolerated before backoff
    adaptive_urp_slack: int = 0
    #: consecutive no-completion ticks (with moves in flight) that count as
    #: stress — the throughput half of the stress signal
    adaptive_stall_ticks: int = 16


@dataclasses.dataclass
class ExecutionResult:
    completed: int
    aborted: int
    dead: int
    ticks: int
    stopped: bool
    tracker_status: dict


class OngoingExecutionError(Exception):
    """Reference sanityCheckDryRun / ongoing-execution guard
    (KafkaCruiseControl.java:216-229)."""


class NoOngoingExecutionError(Exception):
    """Mid-execution concurrency change requested while nothing executes
    (reference rejects ChangeExecutionConcurrency in that case)."""


class ConcurrencyAdjuster:
    """Load-aware movement-cap control (reference executor/ConcurrencyAdjuster):
    multiplicative backoff while the cluster shows stress, additive
    recovery toward the configured cap once it clears.

    Stress per progress tick = under-replicated partitions above the
    execution-start baseline (replicas or leaders on dead brokers — the
    metadata-level URP proxy every ClusterAdmin can serve), OR zero task
    completions for `stall_ticks` consecutive ticks while moves are in
    flight (throughput collapse).  The cluster-wide cap scales with the
    per-broker cap so both back off together.
    """

    def __init__(
        self,
        *,
        base_inter: int,
        base_cluster: int,
        min_cap: int = 1,
        max_cap: int = 64,
        backoff_factor: float = 0.5,
        recover_step: int = 1,
        urp_slack: int = 0,
        stall_ticks: int = 16,
        initial: int | None = None,
        sensors=None,
        journal: ExecutionJournal | None = None,
    ):
        self.base_inter = max(1, int(base_inter))
        self.base_cluster = max(1, int(base_cluster))
        self.min_cap = max(1, int(min_cap))
        self.max_cap = max(self.min_cap, int(max_cap))
        self.backoff_factor = backoff_factor
        self.recover_step = max(1, int(recover_step))
        self.urp_slack = max(0, int(urp_slack))
        self.stall_ticks = max(0, int(stall_ticks))
        self.sensors = sensors
        self.journal = journal
        self.inter_cap = self._clamp(
            initial if initial is not None else self.base_inter
        )
        self.baseline_urps: int | None = None
        self.last_urps = 0
        self.num_backoffs = 0
        self.num_recoveries = 0
        self._idle_ticks = 0

    def _clamp(self, cap: int) -> int:
        return max(self.min_cap, min(int(cap), self.max_cap))

    def caps(self) -> tuple[int, int]:
        """(per-broker inter cap, cluster-wide movement cap)."""
        cluster = max(
            1, round(self.base_cluster * self.inter_cap / self.base_inter)
        )
        return self.inter_cap, min(cluster, self.base_cluster)

    @staticmethod
    def urp_count(topo) -> int:
        """Metadata-level under-replication proxy: partitions whose leader
        or any replica sits on a dead broker."""
        alive = topo.alive_broker_ids()
        return sum(
            1
            for p in topo.partitions
            if p.leader not in alive or any(b not in alive for b in p.replicas)
        )

    def observe(
        self, topo, *, completed: int, in_flight: int, base_inter: int | None = None
    ) -> tuple[int, int]:
        """One progress tick: sample stress, adjust, return active caps."""
        if base_inter is not None and int(base_inter) != self.base_inter:
            # the operator moved the base mid-execution (requested
            # concurrency override) — recover toward the NEW target
            self.base_inter = max(1, int(base_inter))
        urps = self.urp_count(topo)
        self.last_urps = urps
        if self.baseline_urps is None:
            # first tick: the cluster's pre-existing URPs are not this
            # execution's fault and must not trigger immediate backoff
            self.baseline_urps = urps
        if completed > 0 or in_flight == 0:
            self._idle_ticks = 0
        else:
            self._idle_ticks += 1
        stressed = urps > self.baseline_urps + self.urp_slack or (
            self.stall_ticks > 0 and self._idle_ticks >= self.stall_ticks
        )
        prev = self.inter_cap
        if stressed:
            self.inter_cap = max(
                self.min_cap, int(self.inter_cap * self.backoff_factor)
            )
            if self.inter_cap < prev:
                self.num_backoffs += 1
                if self.sensors is not None:
                    self.sensors.counter("executor.adaptive.backoff").inc()
            # one stall episode is one backoff, not one per subsequent tick
            self._idle_ticks = 0
        else:
            ceiling = self._clamp(self.base_inter)
            if self.inter_cap < ceiling:
                self.inter_cap = min(ceiling, self.inter_cap + self.recover_step)
                self.num_recoveries += 1
                if self.sensors is not None:
                    self.sensors.counter("executor.adaptive.recovery").inc()
        if self.inter_cap != prev:
            inter, cluster = self.caps()
            if self.sensors is not None:
                self.sensors.gauge("executor.adaptive.inter-broker-cap").set(inter)
            if self.journal is not None:
                self.journal.append(
                    {"t": "concurrency", "inter": inter, "cluster": cluster,
                     "urps": urps}
                )
            # flight recorder: cap changes land as events on the live
            # execution span (observe() runs on the execution-loop thread,
            # inside the span's context)
            from cruise_control_tpu.common.trace import TRACER

            TRACER.event(
                "adaptive-cap", inter=inter, cluster=cluster, urps=urps,
                stressed=bool(stressed),
            )
        return self.caps()

    def state_json(self) -> dict:
        inter, cluster = self.caps()
        return {
            "interBrokerCap": inter,
            "clusterMovementCap": cluster,
            "baseInterBrokerCap": self.base_inter,
            "underReplicatedPartitions": self.last_urps,
            "baselineUnderReplicatedPartitions": self.baseline_urps or 0,
            "numBackoffs": self.num_backoffs,
            "numRecoveries": self.num_recoveries,
        }


class Executor:
    def __init__(
        self,
        admin: ClusterAdmin,
        *,
        strategy: ReplicaMovementStrategy | None = None,
        topic_names: dict[int, str] | None = None,
        catalog=None,
        sensors=None,
        removal_history_retention_ms: int = 1_209_600_000,
        demotion_history_retention_ms: int = 1_209_600_000,
        notifier=None,
        journal: ExecutionJournal | None = None,
        clock=None,
        anomaly_sink=None,
        tracer=None,
        defer_recovery: bool = False,
    ):
        """notifier (reference ExecutorConfig executor.notifier.class): an
        object with on_execution_finished(result, uuid), called after every
        execution — success, stop or abort.

        journal: durable execution journal (executor/journal.py); an
        unfinished execution found in it is reconciled immediately (see
        class docstring) and the executor starts in RECOVERING state.
        clock: ms-epoch callable — reservation retention and wall
        timestamps ride it, so simulated runs and tests control time.
        anomaly_sink: callable(Anomaly) the stuck-move reaper reports
        EXECUTION_STUCK through (the facade wires the anomaly detector's
        add_anomaly here).

        tracer: flight recorder (common/trace.py) — every execution is an
        `executor.execution` span whose EVENTS are the task transitions
        (riding the same ExecutionTask.observer hook the journal uses),
        reaper actions and adaptive-cap changes; defaults to the
        process-wide TRACER.

        defer_recovery (fleet HA): skip the journal replay at
        construction — reconciliation touches the cluster (throttle
        sweep) and MUST wait for lease acquisition; the fleet manager
        calls reconcile_journal() once the lease is held."""
        from cruise_control_tpu.common.sensors import REGISTRY
        from cruise_control_tpu.common.trace import TRACER

        self.sensors = sensors if sensors is not None else REGISTRY
        self.tracer = tracer if tracer is not None else TRACER
        #: live span of the ongoing execution (task-transition events
        #: attach here from whatever thread drives the loop)
        self._exec_span = None
        self.admin = admin
        self.strategy = strategy
        self.notifier = notifier
        self.journal = journal
        self.anomaly_sink = anomaly_sink
        self._clock = clock or (lambda: int(time.time() * 1000))
        self.topic_names = topic_names or {}
        #: ClusterCatalog resolving global partition ids -> (topic, partition)
        self.catalog = catalog
        self.state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = False
        self._force_stop = False
        self._lock = threading.RLock()
        self.tracker = ExecutionTaskTracker(observer=self._journal_task)
        self._planner: ExecutionTaskPlanner | None = None
        # reference Executor recentlyRemovedBrokers / recentlyDemotedBrokers,
        # timestamped so entries expire after the configured retention
        # (reference ExecutorConfig {removal,demotion}.history.retention.time.ms)
        self._removal_retention_ms = removal_history_retention_ms
        self._demotion_retention_ms = demotion_history_retention_ms
        self._removed_history: dict[int, int] = {}  # broker id -> recorded ms
        self._demoted_history: dict[int, int] = {}
        self.num_executions_started = 0
        self.num_executions_stopped = 0
        self._uuid: str | None = None
        #: re-submission count per dropped reassignment key
        self._reexecutions: dict[tuple[str, int], int] = {}
        #: consecutive unverifiable-completion count per logdir-copy key
        self._intra_unknown: dict[tuple[str, int, int], int] = {}
        #: mid-execution concurrency overrides (reference
        #: Executor.java:485-510 setRequested*MovementConcurrency): the
        #: operator's knob to decelerate or unstick a LIVE execution via
        #: POST /admin.  Consulted every tick; cleared when a new
        #: execution starts so submitted options apply fresh.
        self._requested: dict[str, float | int] = {}
        #: journal-recovered (topic_id, partition_id) -> (name, number)
        #: key mapping — a fresh process has no catalog for proposals it
        #: did not plan itself
        self._key_override: dict[tuple[int, int], tuple[str, int]] = {}
        #: live ConcurrencyAdjuster of the ongoing execution (None outside)
        self._adjuster: ConcurrencyAdjuster | None = None
        #: recovery report of the last journal reconciliation (see
        #: executor_state()["recovery"]); None when the journal was clean
        self._recovery: dict | None = None
        #: stashed remainder of a reconciled execution, consumed by
        #: resume_recovered_execution()
        self._resume_state: tuple | None = None
        #: True after a FencedError aborted an execution (lease lost
        #: mid-batch); cleared when a new execution starts
        self._fenced_abort = False
        #: stuck-move reaper actions within the CURRENT execution (the
        #: decision ledger's outcome record wants the per-execution count,
        #: not the process-lifetime counter)
        self._exec_reaped = 0
        self._exec_started_ms: int | None = None
        #: callable(info: dict) fired when an execution finishes — success,
        #: stop, or fenced abort — riding the same finish path the PR-4
        #: notifier hook does.  The facade wires the decision ledger's
        #: outcome join here (analyzer/ledger.py); best-effort like the
        #: notifier: a broken observer must never fail the execution.
        self.execution_observer = None
        if journal is not None and not defer_recovery:
            self.reconcile_journal()

    def reconcile_journal(self) -> None:
        """Replay the journal and reconcile any unfinished execution
        against the live cluster (see _reconcile_journal), then prune
        terminal journal archives per the retention bounds.  Runs at
        construction by default; fleet HA defers it to lease acquisition
        (and re-runs it on every re-acquisition) — refuses while an
        execution is ongoing.

        The executor is parked in RECOVERING for the DURATION of the
        replay: reconciliation sweeps throttles and rebuilds the tracker,
        so a request-path execution starting mid-sweep would race it —
        the state guard makes execute_proposals reject until the
        reconcile settles."""
        if self.journal is None:
            return
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError(
                    "cannot reconcile the journal mid-execution"
                )
            self.state = ExecutorState.RECOVERING
        settled = False
        try:
            self._reconcile_journal()
            settled = True
        finally:
            with self._lock:
                # _reconcile_journal leaves RECOVERING only when a resume
                # remainder exists (or set NO_TASK itself on the
                # everything-landed path); a clean/failed replay must not
                # leave the guard state wedged
                if self._resume_state is None and (
                    not settled or self.state == ExecutorState.RECOVERING
                ):
                    self.state = ExecutorState.NO_TASK_IN_PROGRESS
        pruned = self.journal.prune_archives(now_ms=self._clock())
        if pruned:
            self.sensors.counter("executor.journal-archives-pruned").inc(pruned)

    # ------------------------------------------------------------------
    # journal hooks

    def _journal_task(self, task: ExecutionTask, state: TaskState, now_ms: int):
        if self.journal is not None:
            self.journal.append(
                {"t": "task", "id": task.execution_id, "state": state.value,
                 "ms": now_ms}
            )
        # same observer, second consumer: every task transition is also a
        # flight-recorder event on the live execution span (bounded there)
        sp = self._exec_span
        if sp is not None:
            sp.event(
                "task",
                id=task.execution_id,
                type=task.task_type.value,
                state=state.value,
                ms=now_ms,
            )

    def _journal_reservations(self):
        if self.journal is not None:
            self.journal.append({
                "t": "reservation",
                "removed": {str(b): ms for b, ms in self._removed_history.items()},
                "demoted": {str(b): ms for b, ms in self._demoted_history.items()},
            })

    # ------------------------------------------------------------------
    # restart reconciliation (journal replay)

    def _reconcile_journal(self):
        """Replay the journal; reconcile an unfinished execution against
        the live cluster.  Runs on construction — cheap (one topology
        fetch + one in-progress listing); the long part (driving the
        remainder) is resume_recovered_execution()."""
        je = self.journal.unfinished_execution()
        if je is None:
            return
        rec_c = lambda name: self.sensors.counter(f"executor.recovery.{name}")  # noqa: E731
        rec_c("executions-recovered").inc()
        now = self._clock()
        self._uuid = je.uuid
        # 1. reservations: removed/demoted broker history survives the crash
        self._removed_history.update(je.removed)
        self._demoted_history.update(je.demoted)
        restored = len(je.removed) + len(je.demoted)
        if restored:
            rec_c("reservations-restored").inc(restored)
        # 2. throttle sweep: a crashed predecessor cannot have cleared its
        # replication throttle — remove it before resuming (or finishing)
        swept = False
        if je.throttle_active:
            try:
                self.admin.clear_replication_throttle()
                swept = True
                rec_c("throttles-swept").inc()
            except Exception:  # noqa: BLE001 — an unreachable admin must not
                # kill construction; the journal keeps showing the throttle
                # active so the NEXT restart retries the sweep
                pass
            if swept:
                # journal only a sweep that actually reached the brokers —
                # recording a failed one would make the leak permanently
                # invisible to future recoveries
                self.journal.append({"t": "throttle_cleared"})
        # 3. task reconciliation against live topology + controller state
        topo = self.admin.topology()
        placement = {
            (p.topic, p.partition): set(p.replicas) for p in topo.partitions
        }
        leaders = {(p.topic, p.partition): p.leader for p in topo.partitions}
        in_prog = self.admin.in_progress_reassignments()
        logdir_pending = (
            self.admin.in_progress_logdir_moves()
            if hasattr(self.admin, "in_progress_logdir_moves")
            else set()
        )
        self.tracker = ExecutionTaskTracker(observer=self._journal_task)
        adopted: dict[tuple[str, int], ExecutionTask] = {}
        adopted_intra: dict[int, tuple[ExecutionTask, dict]] = {}
        pending: list[ExecutionTask] = []
        counts = {"completed": 0, "readopted": 0, "resubmitted": 0}
        for task, key in je.tasks.values():
            self._key_override[(task.proposal.topic, task.proposal.partition)] = key
            self.topic_names.setdefault(task.proposal.topic, key[0])
            if task.state in _TERMINAL:
                self.tracker.add(task)
                continue
            if task.state == TaskState.ABORTING:
                # the reaper / a forced stop was cancelling this move when
                # the process died: finalize the cancellation — whether or
                # not the move landed meanwhile, it must NOT be resubmitted
                # (and COMPLETED is not a legal transition out of ABORTING)
                task.aborted(now)
                self.tracker.add(task)
                continue
            if task.task_type == TaskType.INTER_BROKER_REPLICA_ACTION:
                if placement.get(key) == set(task.proposal.new_replicas):
                    self._force_complete(task, now)
                    counts["completed"] += 1
                elif key in in_prog:
                    # still moving on the controller: re-adopt, never
                    # resubmit.  The CONTROLLER is the authority here, not
                    # the journaled state — crash truncation may have torn
                    # off the IN_PROGRESS record of a move that did reach
                    # the wire, and resubmitting it would double-submit
                    if task.state != TaskState.IN_PROGRESS:
                        task.state = TaskState.IN_PROGRESS
                        task.start_time_ms = now
                    adopted[key] = task
                    counts["readopted"] += 1
                else:
                    # vanished (controller dropped it) or never submitted:
                    # back to PENDING; the resumed loop re-submits it — and
                    # its dead-broker sweep DEAD-marks it if the
                    # destination died while we were down
                    task.state = TaskState.PENDING
                    pending.append(task)
                    if task.start_time_ms >= 0:
                        counts["resubmitted"] += 1
            elif task.task_type == TaskType.LEADER_ACTION:
                if leaders.get(key) == task.proposal.new_leader:
                    self._force_complete(task, now)
                    counts["completed"] += 1
                else:
                    task.state = TaskState.PENDING
                    pending.append(task)
            else:  # intra-broker logdir copy
                keys3 = {
                    (key[0], key[1], b): d_new
                    for (b, _d_old, d_new) in task.proposal.disk_moves
                }
                verify = getattr(self.admin, "logdir_of", None)
                still = {k3: d for k3, d in keys3.items() if k3 in logdir_pending}
                if still:
                    # copies live on the broker win over the journaled
                    # state (same torn-record reasoning as inter-broker)
                    if task.state != TaskState.IN_PROGRESS:
                        task.state = TaskState.IN_PROGRESS
                        task.start_time_ms = now
                    adopted_intra[task.execution_id] = (task, dict(keys3))
                    counts["readopted"] += 1
                elif (
                    task.state == TaskState.IN_PROGRESS
                    and verify is not None
                    and all(verify(*k3) == d for k3, d in keys3.items())
                ):
                    self._force_complete(task, now)
                    counts["completed"] += 1
                else:
                    task.state = TaskState.PENDING
                    pending.append(task)
                    if task.start_time_ms >= 0:
                        counts["resubmitted"] += 1
            self.tracker.add(task)
        for name, n in counts.items():
            if n:
                rec_c(f"tasks-{name}").inc(n)
        self._recovery = {
            "uuid": je.uuid,
            "recoveredMs": now,
            "sweptThrottle": swept,
            "restoredReservations": restored,
            "tasksCompletedWhileDown": counts["completed"],
            "tasksReadopted": counts["readopted"],
            "tasksResubmitted": counts["resubmitted"],
            "tasksPending": len(pending),
        }
        options = ExecutionOptions(**{
            k: v
            for k, v in je.options.items()
            if k in {f.name for f in dataclasses.fields(ExecutionOptions)}
        })
        if pending or adopted or adopted_intra:
            self.state = ExecutorState.RECOVERING
            self._resume_state = (options, adopted, adopted_intra, je.adaptive)
        else:
            # everything landed (or died) while we were down: finish the
            # recovered execution right here
            self._finish_execution(self._result(ticks=0), je.uuid)

    def _force_complete(self, task: ExecutionTask, now: int):
        """Reconciliation found the task's target already live."""
        if task.state == TaskState.PENDING:
            task.in_progress(now)
        task.completed(now)

    @property
    def has_recovered_execution(self) -> bool:
        """True while a reconciled execution awaits resume_recovered_execution."""
        with self._lock:
            return self._resume_state is not None

    def recovery_info(self) -> dict | None:
        with self._lock:
            return dict(self._recovery) if self._recovery else None

    def resume_recovered_execution(self) -> ExecutionResult | None:
        """Drive the reconciled remainder of a recovered execution to
        completion: re-adopted moves keep progressing without re-submission,
        pending/vanished ones flow through the normal drain.  Returns None
        when there is nothing to resume."""
        with self._lock:
            if self._resume_state is None:
                return None
            stash = self._resume_state
            self._resume_state = None
            try:
                options, adopted, adopted_intra, adaptive = stash
                # do NOT reset _stop_requested/_force_stop: an operator stop
                # issued while the executor sat RECOVERING must be honored —
                # the loop below then drains (or force-cancels) the adopted
                # moves instead of driving the recovery to completion
                self.num_executions_started += 1
                self.sensors.counter("executor.execution-started").inc()
                self._fenced_abort = False
                planner = ExecutionTaskPlanner(self.strategy)
                planner.adopt_tasks(self.tracker.tasks(state=TaskState.PENDING))
                self._planner = planner
                self._reexecutions = {}
                self._intra_unknown = {}
            except BaseException:
                # setup failed: put the remainder back so a retried resume
                # (or the next reconciliation) still sees it
                self._resume_state = stash
                raise
        live_proposals = [
            t.proposal for t in self.tracker.tasks() if t.state not in _TERMINAL
        ]
        # the recovery drive is its own ROOT trace: it belongs to no user
        # request (the crashed predecessor's request died with it)
        with self.tracer.span(
            "executor.recovery-resume",
            component="executor",
            root=True,
            num_tasks=len(live_proposals),
            adopted=len(adopted or {}),
        ) as sp:
            self._exec_span = sp
            try:
                result = self._run_guarded(
                    options,
                    live_proposals,
                    in_flight=adopted,
                    intra_in_flight=adopted_intra,
                    adaptive_initial=(adaptive or {}).get("inter"),
                )
            finally:
                self._exec_span = None
            sp.set(
                completed=result.completed, aborted=result.aborted,
                dead=result.dead, stopped=result.stopped,
            )
            return result

    # ------------------------------------------------------------------
    # mid-execution concurrency control (reference Executor.java:485-510,
    # driven by ADMIN ChangeExecutionConcurrencyParameters)

    def set_requested_concurrency(
        self,
        *,
        inter_broker: int | None = None,
        intra_broker: int | None = None,
        leadership: int | None = None,
        progress_check_interval_s: float | None = None,
    ) -> dict:
        """Adjust the concurrency caps of the ongoing execution.

        Each tick of the execution loop reads these instead of the frozen
        ExecutionOptions, so the change takes effect on the next progress
        check — matching the reference's
        setRequestedInterBrokerPartitionMovementConcurrency family.
        Returns the now-effective override map.
        """
        # validate everything BEFORE applying anything: a rejected call
        # must not leave a partial override active on the live execution
        staged: dict[str, float | int] = {}
        for name, v in (
            ("inter_broker", inter_broker),
            ("intra_broker", intra_broker),
            ("leadership", leadership),
        ):
            if v is not None:
                if v < 1:
                    raise ValueError(f"{name} concurrency must be >= 1, got {v}")
                staged[name] = int(v)
        if progress_check_interval_s is not None:
            if progress_check_interval_s <= 0:
                raise ValueError(
                    "progress_check_interval_s must be > 0, got "
                    f"{progress_check_interval_s}"
                )
            staged["interval_s"] = float(progress_check_interval_s)
        with self._lock:
            # checked under the lock: overrides die with the execution
            # (cleared at the next start), so accepting one after the
            # execution finished would 200 a silent no-op
            if not self.has_ongoing_execution:
                raise NoOngoingExecutionError(
                    "cannot change execution concurrency: no ongoing execution"
                )
            self._requested.update(staged)
        return self.requested_concurrency()

    def requested_concurrency(self) -> dict:
        """The active mid-execution overrides (empty when none set)."""
        with self._lock:
            return dict(self._requested)

    def _inter_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("inter_broker")
        return int(v) if v is not None else options.concurrent_partition_movements_per_broker

    def _intra_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("intra_broker")
        return int(v) if v is not None else options.concurrent_intra_broker_partition_movements

    def _leader_cap(self, options: ExecutionOptions) -> int:
        with self._lock:
            v = self._requested.get("leadership")
        return int(v) if v is not None else options.concurrent_leader_movements

    def _interval(self, options: ExecutionOptions) -> float:
        with self._lock:
            v = self._requested.get("interval_s")
        return float(v) if v is not None else options.progress_check_interval_s

    # ------------------------------------------------------------------

    def _pruned(self, history: dict[int, int], retention_ms: int) -> set[int]:
        # readers run on HTTP/detector threads while the execution thread
        # inserts under the lock — prune must take it too
        with self._lock:
            cutoff = self._clock() - retention_ms
            for b in [b for b, ts in history.items() if ts < cutoff]:
                del history[b]
            return set(history)

    @property
    def removed_brokers(self) -> set[int]:
        """Recently removed brokers, expired per the retention window."""
        return self._pruned(self._removed_history, self._removal_retention_ms)

    @property
    def demoted_brokers(self) -> set[int]:
        """Recently demoted brokers, expired per the retention window."""
        return self._pruned(self._demoted_history, self._demotion_retention_ms)

    def drop_removed_brokers(self, broker_ids):
        """Reference ADMIN drop_recently_removed_brokers."""
        with self._lock:
            for b in broker_ids:
                self._removed_history.pop(b, None)
            self._journal_reservations()

    def drop_demoted_brokers(self, broker_ids):
        with self._lock:
            for b in broker_ids:
                self._demoted_history.pop(b, None)
            self._journal_reservations()

    @property
    def has_ongoing_execution(self) -> bool:
        return self.state != ExecutorState.NO_TASK_IN_PROGRESS

    def stop_execution(self, *, force: bool = False):
        """Reference Executor.userTriggeredStopExecution (+ force stop :1145)."""
        with self._lock:
            if self.has_ongoing_execution:
                self._stop_requested = True
                self._force_stop = force
                self.num_executions_stopped += 1
                self.state = ExecutorState.STOPPING_EXECUTION
                # reference Executor execution-stopped gauge (:118-125,257)
                self.sensors.counter("executor.execution-stopped").inc()
                if force:
                    self.sensors.counter("executor.execution-stopped.forced").inc()

    def execute_proposals(
        self,
        proposals: list[ExecutionProposal],
        options: ExecutionOptions | None = None,
        *,
        uuid: str | None = None,
        removed_brokers: set[int] | None = None,
        demoted_brokers: set[int] | None = None,
        strategy_context: dict | None = None,
        strategy: ReplicaMovementStrategy | None = None,
    ) -> ExecutionResult:
        """Reference Executor.executeProposals():395 (synchronous variant).

        strategy: per-execution ordering override (reference per-request
        replica_movement_strategies); falls back to the configured default."""
        from cruise_control_tpu.fleet.leases import FencedError

        options = options or ExecutionOptions()
        with self._lock:
            if self.has_ongoing_execution:
                raise OngoingExecutionError("an execution is already in progress")
            self.state = ExecutorState.STARTING_EXECUTION
            try:
                self._stop_requested = False
                self._force_stop = False
                self._uuid = uuid
                self.num_executions_started += 1
                # reference Executor execution-started sensor (:118-125)
                self.sensors.counter("executor.execution-started").inc()
                now = self._clock()
                for b in removed_brokers or ():
                    self._removed_history[b] = now
                for b in demoted_brokers or ():
                    self._demoted_history[b] = now
                self.tracker = ExecutionTaskTracker(observer=self._journal_task)
                self._reexecutions = {}
                self._intra_unknown = {}
                self._requested = {}  # overrides die with the previous one
                self._recovery = None
                self._fenced_abort = False
                self._exec_reaped = 0
                self._exec_started_ms = now
                self._planner = ExecutionTaskPlanner(strategy or self.strategy)
                tasks = self._planner.add_execution_proposals(
                    proposals, strategy_context
                )
                for t in tasks:
                    self.tracker.add(t)
                if self.journal is not None:
                    # durable BEFORE the first cluster mutation: a crash at
                    # any later point finds every task + reservation in the
                    # journal
                    self.journal.start_execution({
                        "uuid": uuid,
                        "ms": now,
                        "options": dataclasses.asdict(options),
                        "tasks": [
                            task_to_journal(t, self._partition_key(t.proposal))
                            for t in tasks
                        ],
                        "removed": {
                            str(b): ms for b, ms in self._removed_history.items()
                        },
                        "demoted": {
                            str(b): ms for b, ms in self._demoted_history.items()
                        },
                    })
            except BaseException as e:
                # a setup failure (bad proposals, fenced journal start, ...)
                # must not wedge the executor in STARTING_EXECUTION — that
                # state blocks every later execution AND reconciliation
                self.state = ExecutorState.NO_TASK_IN_PROGRESS
                self._planner = None
                if isinstance(e, FencedError):
                    self._fenced_abort = True
                    self.sensors.counter("executor.fenced-aborts").inc()
                raise
        with self.tracer.span(
            "executor.execution",
            component="executor",
            uuid=uuid,
            num_tasks=len(tasks),
        ) as sp:
            self._exec_span = sp
            try:
                result = self._run_guarded(options, proposals)
            finally:
                self._exec_span = None
            sp.set(
                completed=result.completed, aborted=result.aborted,
                dead=result.dead, stopped=result.stopped, ticks=result.ticks,
            )
            return result

    def _run_guarded(
        self,
        options: ExecutionOptions,
        proposals,
        *,
        in_flight=None,
        intra_in_flight=None,
        adaptive_initial: int | None = None,
    ) -> ExecutionResult:
        """Throttle lifecycle + state reset around the execution loop, in
        try/finally so no exit path — exception included — leaks a
        replication throttle onto the brokers or wedges the executor state.

        FencedError (fleet HA) aborts the batch cleanly: the zombie's
        cleanup calls are themselves fenced (it must not clear a throttle
        the NEW holder's reconciliation is about to sweep), the local
        state still resets, nothing is journaled, and the error
        propagates so the caller knows the lease is gone."""
        from cruise_control_tpu.fleet.leases import FencedError

        throttle = ReplicationThrottleHelper(
            self.admin, options.replication_throttle_bytes_per_s,
            journal=self.journal,
        )
        uuid = self._uuid
        try:
            try:
                throttle.set_throttles(proposals, self.topic_names)
                result = self._run(
                    options, in_flight=in_flight,
                    intra_in_flight=intra_in_flight,
                    adaptive_initial=adaptive_initial,
                )
            finally:
                try:
                    throttle.clear_throttles()
                finally:
                    with self._lock:
                        self.state = ExecutorState.NO_TASK_IN_PROGRESS
                        self._planner = None
                        self._adjuster = None
            # inside the guard: a lease lost between the last task and the
            # finished-record append is STILL a fenced abort, not an
            # anonymous exception
            self._finish_execution(result, uuid)
        except FencedError:
            with self._lock:
                self._fenced_abort = True
            self.sensors.counter("executor.fenced-aborts").inc()
            # the observer still hears about the episode's end: a fenced
            # abort IS this execution's outcome (the new holder resumes
            # under its own decision)
            self._notify_execution_observer(
                result=None, uuid=uuid, fenced=True
            )
            raise
        return result

    def _notify_execution_observer(self, *, result, uuid, fenced: bool):
        obs = self.execution_observer
        if obs is None:
            return
        now = self._clock()
        started = self._exec_started_ms
        info = {
            "uuid": uuid,
            "startedMs": started,
            "finishedMs": now,
            "durationS": (
                round((now - started) / 1000.0, 3) if started is not None else None
            ),
            "completed": result.completed if result is not None else 0,
            "aborted": result.aborted if result is not None else 0,
            "dead": result.dead if result is not None else 0,
            "stopped": bool(result.stopped) if result is not None else False,
            "fencedAbort": bool(fenced),
            "reaped": self._exec_reaped,
        }
        try:
            obs(info)
        except Exception:  # noqa: BLE001 — observers must not fail the
            # execution (same contract as the notifier hook above)
            pass

    def _result(self, *, ticks: int) -> ExecutionResult:
        return ExecutionResult(
            completed=self.tracker.count(state=TaskState.COMPLETED),
            aborted=self.tracker.count(state=TaskState.ABORTED),
            dead=self.tracker.count(state=TaskState.DEAD),
            ticks=ticks,
            stopped=self._stop_requested,
            tracker_status=self.tracker.status(),
        )

    def _finish_execution(self, result: ExecutionResult, uuid: str | None):
        if self.journal is not None:
            self.journal.append({
                "t": "finished",
                "ms": self._clock(),
                "result": {
                    "completed": result.completed,
                    "aborted": result.aborted,
                    "dead": result.dead,
                    "stopped": result.stopped,
                },
            })
        with self._lock:
            self.state = ExecutorState.NO_TASK_IN_PROGRESS
        if self.notifier is not None:
            try:
                self.notifier.on_execution_finished(result, uuid)
            except Exception:  # noqa: BLE001 — a broken notifier must not fail the execution
                pass
        self._notify_execution_observer(result=result, uuid=uuid, fenced=False)

    # ------------------------------------------------------------------

    def _maybe_alert_slow_task(self, task, data_bytes, floor_mb_s, options, now):
        """Reference slow-task alerting (ExecutorConfig:142-158): alert once
        when a move runs past task.execution.alerting.threshold.ms AND its
        data rate (bytes -> MB/s) is under the configured floor."""
        if task.alert_time_ms >= 0:
            return
        elapsed_ms = now - task.start_time_ms
        if elapsed_ms <= options.task_execution_alerting_s * 1000:
            return
        if data_bytes / 1e6 / max(elapsed_ms / 1000.0, 1e-9) >= floor_mb_s:
            return
        task.alert_time_ms = now
        self.sensors.counter("executor.slow-task-alert").inc()
        if self.notifier is not None and hasattr(self.notifier, "on_task_alert"):
            try:
                self.notifier.on_task_alert(task)
            except Exception:  # noqa: BLE001 — a broken notifier must not fail the execution
                pass

    def _reap_stuck_move(
        self, task, key, in_flight, watermark, now: int, stalled_ms: int
    ):
        """Stuck-move reaper enforcement: cancel the wedged reassignment —
        per-partition rollback where the controller supports it, DEAD
        otherwise — journal it, raise EXECUTION_STUCK, and let the rest of
        the batch keep flowing."""
        cancel = getattr(self.admin, "cancel_partition_reassignments", None)
        rolled_back = False
        if cancel is not None:
            try:
                cancel([key])
                rolled_back = True
            except Exception:  # noqa: BLE001 — an uncancellable move still
                # must not wedge the batch; fall through to DEAD
                rolled_back = False
        if rolled_back:
            task.aborting(now)
            task.aborted(now)
            self.sensors.counter("executor.reaper.rollback").inc()
        else:
            task.kill(now)
        del in_flight[key]
        watermark.pop(key, None)
        self._exec_reaped += 1
        self.sensors.counter("executor.reaper.stuck-task").inc()
        sp = self._exec_span
        if sp is not None:
            sp.event(
                "reaped",
                id=task.execution_id,
                mode="rollback" if rolled_back else "dead",
                stalled_s=round(stalled_ms / 1000.0, 3),
            )
        if self.journal is not None:
            self.journal.append({
                "t": "reaped",
                "id": task.execution_id,
                "mode": "rollback" if rolled_back else "dead",
                "ms": now,
            })
        if self.anomaly_sink is not None:
            from cruise_control_tpu.detector.anomalies import ExecutionStuck

            try:
                self.anomaly_sink(ExecutionStuck(
                    topic=key[0],
                    partition=key[1],
                    execution_id=task.execution_id,
                    uuid=self._uuid or "",
                    stalled_s=stalled_ms / 1000.0,
                    rolled_back=rolled_back,
                ))
            except Exception:  # noqa: BLE001 — anomaly delivery is best-effort
                pass

    def _run(
        self,
        options: ExecutionOptions,
        *,
        in_flight: dict[tuple[str, int], ExecutionTask] | None = None,
        intra_in_flight: dict | None = None,
        adaptive_initial: int | None = None,
    ) -> ExecutionResult:
        """The proposal execution loop (reference ProposalExecutionRunnable.run:749):
        phase 1 — inter/intra-broker replica moves; phase 2 — leadership.

        in_flight / intra_in_flight: moves re-adopted by restart
        reconciliation — tracked to completion without re-submission.
        adaptive_initial: journaled adaptive cap a resumed execution picks
        back up — a cluster that was stressed moments before the crash
        must not be re-hit at full base concurrency."""
        planner = self._planner
        assert planner is not None
        in_flight = in_flight if in_flight is not None else {}
        #: intra-broker tasks still copying between logdirs:
        #: execution id -> (task, {(topic, partition, broker): target disk})
        intra_in_flight = intra_in_flight if intra_in_flight is not None else {}
        ticks = 0
        simulated = hasattr(self.admin, "tick")
        # admins that cannot report logdir-copy progress complete intra
        # moves on submit (the pre-KIP-113 behavior)
        track_intra = hasattr(self.admin, "in_progress_logdir_moves")
        # stuck-move reaper state: key -> (last observed remaining bytes,
        # last progress ms).  remaining-bytes sampling is an optional admin
        # capability; without it the watermark only advances on completion.
        reap_timeout_ms = (
            int(options.reaper_stuck_timeout_s * 1000)
            if options.reaper_stuck_timeout_s
            else None
        )
        remaining_fn = getattr(self.admin, "reassignment_remaining_bytes", None)
        watermark: dict[tuple[str, int], tuple[float | None, int]] = {}
        adjuster = None
        if options.adaptive_enabled:
            adjuster = ConcurrencyAdjuster(
                base_inter=self._inter_cap(options),
                base_cluster=options.max_num_cluster_movements,
                min_cap=options.adaptive_min_concurrency,
                max_cap=options.adaptive_max_concurrency,
                backoff_factor=options.adaptive_backoff_factor,
                recover_step=options.adaptive_recover_step,
                urp_slack=options.adaptive_urp_slack,
                stall_ticks=options.adaptive_stall_ticks,
                initial=adaptive_initial,
                sensors=self.sensors,
                journal=self.journal,
            )
            self._adjuster = adjuster

        def now_ms() -> int:
            return self._clock() if not simulated else ticks * 1000

        # intra-broker completions land AFTER the adjuster's observe() in
        # the tick that collects them — carried into the next tick so an
        # intra-heavy execution is not falsely judged throughput-stalled
        carried_completions = 0

        # --- phase 1: replica movements ---
        self.state = ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        while ticks < options.max_ticks:
            if self._stop_requested:
                self._handle_stop(in_flight, now_ms())
                if self._force_stop:
                    # logdir copies cannot be cancelled over the wire; the
                    # tasks are recorded aborted (reference behavior: an
                    # intra move is 'cancelled' by moving back later)
                    for t, _keys in intra_in_flight.values():
                        t.aborting(now_ms())
                        t.aborted(now_ms())
                    intra_in_flight.clear()
                    break
                # graceful stop: submit nothing new, but keep collecting
                # completions until everything in flight drains — an
                # untracked reassignment or logdir copy would otherwise sit
                # IN_PROGRESS in the tracker forever and the result counts
                # would not add up to the task total
                if not in_flight and not intra_in_flight:
                    break
            # collect completions.  A key leaving the in-progress set does
            # NOT prove the move landed: the controller may have dropped the
            # reassignment (reference Executor.maybeReexecuteTasks:1430) —
            # verify against the topology and re-submit dropped tasks, up to
            # a bound, before declaring them DEAD.
            in_progress = self.admin.in_progress_reassignments()
            # ONE topology snapshot per tick feeds both the landed-check and
            # the dead-broker sweep below (on a real cluster each topology()
            # is a wire Metadata round trip)
            topo = self.admin.topology()
            placement = None
            completed_this_tick = carried_completions
            carried_completions = 0
            for key, task in list(in_flight.items()):
                if key not in in_progress:
                    if placement is None:
                        placement = {
                            (p.topic, p.partition): set(p.replicas)
                            for p in topo.partitions
                        }
                    if placement.get(key) == set(task.proposal.new_replicas):
                        task.completed(now_ms())
                        del in_flight[key]
                        watermark.pop(key, None)
                        completed_this_tick += 1
                        continue
                    n = self._reexecutions.get(key, 0)
                    if n >= options.max_reexecution_attempts:
                        task.kill(now_ms())
                        del in_flight[key]
                        watermark.pop(key, None)
                        continue
                    self._reexecutions[key] = n + 1
                    # reference Executor sensor analog for re-executed tasks
                    self.sensors.counter("executor.task-reexecuted").inc()
                    self.admin.reassign_partitions([
                        ReassignmentSpec(
                            topic=key[0],
                            partition=key[1],
                            new_replicas=tuple(task.proposal.new_replicas),
                            data_to_move=task.proposal.inter_broker_data_to_move,
                        )
                    ])
                else:
                    self._maybe_alert_slow_task(
                        task,
                        task.proposal.inter_broker_data_to_move,
                        options.inter_broker_rate_alerting_mb_s,
                        options,
                        now_ms(),
                    )
            # stuck-move reaper: a move whose progress watermark stalls past
            # the timeout is cancelled (rollback where supported, else DEAD)
            # instead of holding its concurrency slots until max_ticks
            if reap_timeout_ms is not None and in_flight:
                rem_bytes = remaining_fn() if remaining_fn is not None else {}
                for key, task in list(in_flight.items()):
                    if key not in in_progress:
                        continue
                    rem = rem_bytes.get(key)
                    last_rem, last_ms = watermark.get(key, (None, now_ms()))
                    if key not in watermark:
                        watermark[key] = (rem, now_ms())
                    elif rem is not None and (last_rem is None or rem < last_rem):
                        watermark[key] = (rem, now_ms())  # progress observed
                    elif now_ms() - last_ms >= reap_timeout_ms:
                        self._reap_stuck_move(
                            task, key, in_flight, watermark,
                            now_ms(), now_ms() - last_ms,
                        )
            # mark tasks dead when a destination broker died mid-move
            alive = topo.alive_broker_ids()
            for key, task in list(in_flight.items()):
                if not set(task.proposal.new_replicas) <= alive:
                    task.kill(now_ms())
                    del in_flight[key]
                    watermark.pop(key, None)
            # same sweep for logdir copies: a copy on a dead broker can
            # never confirm — without this the phase-1 loop would spin on
            # it until max_ticks
            for eid, (t, keys) in list(intra_in_flight.items()):
                if any(b not in alive for (_tn, _pn, b) in keys):
                    t.kill(now_ms())
                    del intra_in_flight[eid]

            # load-aware adaptive caps: sample stress, adjust (AIMD)
            inter_cap = self._inter_cap(options)
            cluster_cap = options.max_num_cluster_movements
            if adjuster is not None:
                inter_cap, cluster_cap = adjuster.observe(
                    topo,
                    completed=completed_this_tick,
                    in_flight=len(in_flight) + len(intra_in_flight),
                    base_inter=self._inter_cap(options),
                )

            # drain new tasks within caps (per-broker AND the global
            # max.num.cluster.movements budget) — unless a graceful stop is
            # draining the in-flight set
            if self._stop_requested:
                new_tasks, intra = [], []
            else:
                ready = self._ready_brokers(options, in_flight, topo, cap=inter_cap)
                budget = max(
                    0,
                    cluster_cap - len(in_flight) - len(intra_in_flight),
                )
                new_tasks = planner.get_inter_broker_replica_movement_tasks(
                    ready, set(in_flight), max_total=budget
                )
                # intra-broker moves share the global movement budget:
                # whatever the inter-broker drain left of it this tick.
                # Copies still in flight consume their broker's slots
                # (num.concurrent.intra.broker.partition.movements caps
                # CONCURRENT copies per broker, not submissions per tick)
                intra_used: dict[int, int] = {}
                for _t, keys in intra_in_flight.values():
                    for (_tn, _pn, b) in keys:
                        intra_used[b] = intra_used.get(b, 0) + 1
                intra_cap = self._intra_cap(options)
                intra = planner.get_intra_broker_replica_movement_tasks(
                    {b: max(0, intra_cap - intra_used.get(b, 0)) for b in alive},
                    max_total=max(0, budget - len(new_tasks)),
                )
            if new_tasks:
                specs = []
                for t in new_tasks:
                    t.in_progress(now_ms())
                    key = self._partition_key(t.proposal)
                    in_flight[key] = t
                    specs.append(
                        ReassignmentSpec(
                            topic=key[0],
                            partition=key[1],
                            new_replicas=tuple(t.proposal.new_replicas),
                            data_to_move=t.proposal.inter_broker_data_to_move,
                        )
                    )
                self.admin.reassign_partitions(specs)
            for t in intra:
                t.in_progress(now_ms())
                tname, pnum = self._partition_key(t.proposal)
                self.admin.alter_replica_logdirs(
                    [
                        (tname, pnum, b, d_new)
                        for (b, _d_old, d_new) in t.proposal.disk_moves
                    ]
                )
                if track_intra:
                    intra_in_flight[t.execution_id] = (t, {
                        (tname, pnum, b): d_new
                        for (b, _d_old, d_new) in t.proposal.disk_moves
                    })
                else:
                    t.completed(now_ms())
                    carried_completions += 1
            # intra-broker copy progress (reference ExecutorAdminUtils
            # DescribeLogDirs future replicas): a task completes when none
            # of its (t, p, broker) copies are still in flight; long slow
            # copies alert like inter-broker moves
            if intra_in_flight:
                still = self.admin.in_progress_logdir_moves()
                verify = getattr(self.admin, "logdir_of", None)
                for eid, (t, keys) in list(intra_in_flight.items()):
                    pending = {}
                    for key3, disk in keys.items():
                        if key3 in still:
                            pending[key3] = disk
                            # observed pending again: the unverifiable
                            # bound is CONSECUTIVE ticks, so re-observation
                            # resets it (transient blips hours apart must
                            # not accumulate into a kill)
                            self._intra_unknown.pop(key3, None)
                            continue
                        if verify is None:
                            continue  # cannot verify: disappearance = done
                        # disappearance does NOT prove the copy landed (a
                        # broker restart aborts the future log) — check the
                        # replica's actual dir, like the inter-broker path
                        # re-verifies against the topology
                        actual = verify(*key3)
                        if actual == disk:
                            self._intra_unknown.pop(key3, None)
                            continue
                        if actual is None:
                            # unverifiable (e.g. broker unreachable): keep
                            # polling, but bounded — a partitioned broker
                            # must not hold the loop open until max_ticks
                            u = self._intra_unknown.get(key3, 0) + 1
                            self._intra_unknown[key3] = u
                            if u > options.max_intra_verify_failures:
                                t.kill(now_ms())
                                del intra_in_flight[eid]
                                pending = None
                                break
                            pending[key3] = disk
                            continue
                        n = self._reexecutions.get(key3, 0)
                        if n >= options.max_reexecution_attempts:
                            t.kill(now_ms())
                            del intra_in_flight[eid]
                            pending = None
                            break
                        self._reexecutions[key3] = n + 1
                        self.sensors.counter("executor.task-reexecuted").inc()
                        try:
                            self.admin.alter_replica_logdirs([(*key3, disk)])
                        except Exception:  # noqa: BLE001 — a failed resubmit
                            # must not abort the whole execution; the copy
                            # stays pending and the bounds above decide
                            pass
                        # a resubmitted copy starts a fresh consecutive
                        # unverifiable window
                        self._intra_unknown.pop(key3, None)
                        pending[key3] = disk
                    if pending is None:
                        continue
                    if not pending:
                        t.completed(now_ms())
                        del intra_in_flight[eid]
                        carried_completions += 1
                        continue
                    intra_in_flight[eid] = (t, pending)
                    self._maybe_alert_slow_task(
                        t,
                        t.proposal.intra_broker_data_to_move,
                        options.intra_broker_rate_alerting_mb_s,
                        options,
                        now_ms(),
                    )

            if (
                not in_flight
                and not intra_in_flight
                and not planner.remaining_inter_broker_moves
                and not planner.remaining_intra_broker_moves
            ):
                break
            ticks += 1
            if simulated:
                self.admin.tick(self._interval(options))
            else:
                time.sleep(self._interval(options))

        # --- phase 2: leadership movements ---
        if not self._stop_requested:
            self.state = ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS
            while not self._stop_requested:
                batch = planner.get_leadership_movement_tasks(
                    min(
                        self._leader_cap(options),
                        options.max_num_cluster_movements,
                    )
                )
                if not batch:
                    break
                specs = []
                for t in batch:
                    t.in_progress(now_ms())
                    tname, pnum = self._partition_key(t.proposal)
                    specs.append(
                        LeadershipSpec(
                            topic=tname,
                            partition=pnum,
                            preferred_leader=t.proposal.new_leader,
                        )
                    )
                self.admin.elect_leaders(specs)
                # confirm against the topology; moves not confirmed within
                # leader.movement.timeout.ms are DEAD (reference
                # ExecutorConfig leader.movement.timeout.ms + the executor's
                # leadership wait loop, Executor.java:1091-1136)
                pending = {self._partition_key(t.proposal): t for t in batch}
                deadline = now_ms() + int(options.leader_movement_timeout_s * 1000)
                while pending:
                    topo2 = self.admin.topology()
                    alive2 = topo2.alive_broker_ids()
                    parts = {(p.topic, p.partition): p for p in topo2.partitions}
                    for key, t in list(pending.items()):
                        target = t.proposal.new_leader
                        p = parts.get(key)
                        if p is not None and p.leader == target:
                            t.completed(now_ms())
                            del pending[key]
                        elif target not in alive2:
                            # target broker died — the election can never be
                            # confirmed: DEAD immediately, don't burn the
                            # timeout
                            t.kill(now_ms())
                            del pending[key]
                        elif p is None or target not in p.replicas:
                            # prerequisite replica placement never landed
                            # (e.g. its move task went DEAD) — cancel the
                            # dependent leadership move
                            t.aborting(now_ms())
                            t.aborted(now_ms())
                            del pending[key]
                    if not pending:
                        break
                    if self._stop_requested:
                        # stop mid-confirmation: unconfirmed moves are
                        # aborted, not left dangling
                        for t in pending.values():
                            t.aborting(now_ms())
                            t.aborted(now_ms())
                        pending.clear()
                        break
                    if now_ms() >= deadline:
                        for t in pending.values():
                            t.kill(now_ms())
                            self.sensors.counter(
                                "executor.leader-movement-timeout"
                            ).inc()
                        break
                    if simulated:
                        self.admin.tick(self._interval(options))
                        ticks += 1
                    else:
                        time.sleep(self._interval(options))

        # abort anything still pending after a stop
        for t in self.tracker.tasks(state=TaskState.PENDING):
            t.in_progress(now_ms())
            t.aborting(now_ms())
            t.aborted(now_ms())

        return self._result(ticks=ticks)

    def _handle_stop(self, in_flight, now: int):
        """Graceful stop finishes nothing new; forced stop cancels in-flight
        reassignments (reference Executor.java:1145)."""
        if self._force_stop:
            self.admin.cancel_reassignments()
            for task in in_flight.values():
                task.aborting(now)
                task.aborted(now)
            in_flight.clear()

    def _ready_brokers(
        self, options: ExecutionOptions, in_flight, topo=None, cap: int | None = None
    ) -> dict[int, int]:
        if cap is None:
            cap = self._inter_cap(options)
        if topo is None:
            topo = self.admin.topology()
        alive = topo.alive_broker_ids()
        used: dict[int, int] = {}
        for task in in_flight.values():
            p = task.proposal
            for b in set(p.old_replicas) ^ set(p.new_replicas):
                used[b] = used.get(b, 0) + 1
        ready = {b: max(0, cap - used.get(b, 0)) for b in alive}
        # dead brokers do no replication work: moves off them are only
        # bounded by the destination's slots (replicas rebuild from alive
        # leaders — reference executes dead-broker evacuation uncapped on
        # the failed side)
        for b in topo.broker_ids():
            if b not in alive:
                ready[b] = 1_000_000
        return ready

    def _partition_key(self, proposal: ExecutionProposal) -> tuple[str, int]:
        """(topic name, partition number) for a proposal: the catalog maps
        the array model's global partition id; without one, proposal ids are
        taken at face value (fixture-built proposals).  Journal-recovered
        proposals carry their original keys (a fresh process has no catalog
        for a predecessor's plan)."""
        override = self._key_override.get((proposal.topic, proposal.partition))
        if override is not None:
            return override
        if self.catalog is not None:
            return self.catalog.partition_key(proposal.partition)
        return (
            self.topic_names.get(proposal.topic, str(proposal.topic)),
            proposal.partition,
        )

    # ------------------------------------------------------------------

    def executor_state(self) -> dict:
        """STATE endpoint payload (reference ExecutorState JSON)."""
        out = {
            "state": self.state.value,
            "numFinishedMovements": self.tracker.count(state=TaskState.COMPLETED),
            "numTotalMovements": len(self.tracker.tasks()),
            "finishedDataMovementMB": self.tracker.finished_data_bytes(),
            # per-type PENDING/IN_PROGRESS/ABORTING/ABORTED/DEAD/COMPLETED
            # breakdown (reference ExecutorState task-state sets)
            "taskStatus": self.tracker.status(),
            "numReexecutedTasks": sum(self._reexecutions.values()),
            "recentlyRemovedBrokers": sorted(self.removed_brokers),
            "recentlyDemotedBrokers": sorted(self.demoted_brokers),
            "numExecutionsStarted": self.num_executions_started,
            "numExecutionsStopped": self.num_executions_stopped,
            "triggeredUserTaskId": self._uuid,
            # operator-requested mid-execution overrides, if any (reference
            # ExecutorState requested*MovementConcurrency fields)
            "requestedConcurrency": self.requested_concurrency(),
        }
        adjuster = self._adjuster
        if adjuster is not None:
            out["adaptiveConcurrency"] = adjuster.state_json()
        recovery = self.recovery_info()
        if recovery is not None:
            out["recovery"] = recovery
        with self._lock:
            if self._fenced_abort:
                # the last execution aborted on a lost lease (fleet HA)
                out["fencedAbort"] = True
        return out
