"""Pluggable replica-movement ordering strategies.

Reference: executor/strategy/ReplicaMovementStrategy.java (SPI),
BaseReplicaMovementStrategy.java (execution-id order),
PrioritizeLargeReplicaMovementStrategy / PrioritizeSmallReplicaMovementStrategy,
PostponeUrpReplicaMovementStrategy (URP moves last).  Strategies chain:
`a.chain(b)` sorts by a's key, breaking ties with b's (reference
ReplicaMovementStrategy.chain).
"""

from __future__ import annotations

from cruise_control_tpu.executor.tasks import ExecutionTask


class ReplicaMovementStrategy:
    """Returns a sort key per task; lower sorts (executes) first."""

    name = "BaseReplicaMovementStrategy"

    def key(self, task: ExecutionTask, context: dict):
        return task.execution_id

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        outer = self

        class _Chained(ReplicaMovementStrategy):
            name = f"{outer.name}->{nxt.name}"

            def key(self, task, context):
                return (outer.key(task, context), nxt.key(task, context))

        return _Chained()

    def order(self, tasks: list[ExecutionTask], context: dict | None = None) -> list[ExecutionTask]:
        context = context or {}
        return sorted(tasks, key=lambda t: (self.key(t, context), t.execution_id))


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    pass


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    """Biggest data movements first (reference
    executor/strategy/PrioritizeLargeReplicaMovementStrategy.java)."""

    name = "PrioritizeLargeReplicaMovementStrategy"

    def key(self, task, context):
        return -task.proposal.inter_broker_data_to_move


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    name = "PrioritizeSmallReplicaMovementStrategy"

    def key(self, task, context):
        return task.proposal.inter_broker_data_to_move


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move partitions without under-replicated replicas first (reference
    executor/strategy/PostponeUrpReplicaMovementStrategy.java).  Context key
    'urp_partitions' is a set of (topic, partition)."""

    name = "PostponeUrpReplicaMovementStrategy"

    def key(self, task, context):
        urp = context.get("urp_partitions", set())
        return 1 if (task.proposal.topic, task.proposal.partition) in urp else 0


STRATEGIES_BY_NAME = {
    s.name: s
    for s in (
        BaseReplicaMovementStrategy(),
        PrioritizeLargeReplicaMovementStrategy(),
        PrioritizeSmallReplicaMovementStrategy(),
        PostponeUrpReplicaMovementStrategy(),
    )
}


def resolve_strategy_chain(
    names: list[str], allowed: set[str] | None = None
) -> ReplicaMovementStrategy:
    """Resolve an ordered strategy-name list into one chained strategy
    (reference ExecutorConfig default.replica.movement.strategies +
    per-request replica_movement_strategies).

    Names resolve from the builtin registry or as dotted paths to custom
    classes; `allowed` (reference replica.movement.strategies — the pool of
    supported strategies) restricts what callers may reference."""
    if not names:
        raise ValueError("empty strategy list")
    resolved = []
    for n in names:
        if allowed is not None and n not in allowed:
            raise ValueError(
                f"strategy {n!r} is not in replica.movement.strategies {sorted(allowed)}"
            )
        if n in STRATEGIES_BY_NAME:
            resolved.append(STRATEGIES_BY_NAME[n])
            continue
        if "." in n:
            import importlib

            mod, _, cls = n.rpartition(".")
            resolved.append(getattr(importlib.import_module(mod), cls)())
            continue
        raise ValueError(
            f"unknown replica movement strategy {n!r}; "
            f"builtins: {sorted(STRATEGIES_BY_NAME)}"
        )
    chain = resolved[0]
    for s in resolved[1:]:
        chain = chain.chain(s)
    return chain
