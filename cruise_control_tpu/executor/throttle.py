"""Replication throttling around an execution.

Reference: executor/ReplicationThrottleHelper.java:32-47 — sets
leader/follower throttled rates + throttled-replica lists on the brokers
and topics involved in an execution, and cleans them up afterwards (even
on failure).
"""

from __future__ import annotations

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.admin import ClusterAdmin


class ReplicationThrottleHelper:
    def __init__(self, admin: ClusterAdmin, throttle_rate_bytes_per_s: float | None):
        self.admin = admin
        self.rate = throttle_rate_bytes_per_s
        self._active = False

    def set_throttles(self, proposals: list[ExecutionProposal], topic_names: dict[int, str]):
        if self.rate is None:
            return
        topics = {
            topic_names.get(p.topic, str(p.topic))
            for p in proposals
            if p.has_replica_action
        }
        if topics:
            self.admin.set_replication_throttle(self.rate, topics)
            self._active = True

    def clear_throttles(self):
        if self._active:
            self.admin.clear_replication_throttle()
            self._active = False
