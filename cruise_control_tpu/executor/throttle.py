"""Replication throttling around an execution.

Reference: executor/ReplicationThrottleHelper.java:32-47 — sets
leader/follower throttled rates + throttled-replica lists on the brokers
and topics involved in an execution, and cleans them up afterwards (even
on failure).

Every set/clear is recorded in the execution journal when one is attached
(executor/journal.py), so a restarted executor can sweep throttles a
crashed predecessor leaked onto the brokers.
"""

from __future__ import annotations

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.admin import ClusterAdmin


class ReplicationThrottleHelper:
    def __init__(
        self,
        admin: ClusterAdmin,
        throttle_rate_bytes_per_s: float | None,
        *,
        journal=None,
    ):
        self.admin = admin
        self.rate = throttle_rate_bytes_per_s
        self.journal = journal
        self._active = False

    def set_throttles(self, proposals: list[ExecutionProposal], topic_names: dict[int, str]):
        if self.rate is None:
            return
        topics = {
            topic_names.get(p.topic, str(p.topic))
            for p in proposals
            if p.has_replica_action
        }
        if topics:
            # journal FIRST: a crash between the journal write and the
            # broker config change sweeps a throttle that never landed
            # (harmless); the reverse order would leak one silently
            if self.journal is not None:
                self.journal.append(
                    {"t": "throttle_set", "rate": self.rate,
                     "topics": sorted(topics)}
                )
            self.admin.set_replication_throttle(self.rate, topics)
            self._active = True

    def clear_throttles(self):
        if self._active:
            self.admin.clear_replication_throttle()
            self._active = False
            if self.journal is not None:
                self.journal.append({"t": "throttle_cleared"})
