"""Execution task planning: proposals -> strategy-ordered task queues with
per-broker concurrency-aware draining.

Reference: executor/ExecutionTaskPlanner.java:63 (addExecutionProposals),
:280-295 (leadership drain), :314+ (getInterBrokerReplicaMovementTasks —
round-robin over ready brokers so no broker starves).
"""

from __future__ import annotations

import dataclasses

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.strategy import (
    BaseReplicaMovementStrategy,
    ReplicaMovementStrategy,
)
from cruise_control_tpu.executor.tasks import ExecutionTask, TaskType


class ExecutionTaskPlanner:
    def __init__(self, strategy: ReplicaMovementStrategy | None = None):
        self.strategy = strategy or BaseReplicaMovementStrategy()
        self._next_id = 0
        self._inter: list[ExecutionTask] = []
        self._intra: list[ExecutionTask] = []
        self._leadership: list[ExecutionTask] = []

    def _task(self, proposal: ExecutionProposal, tt: TaskType) -> ExecutionTask:
        t = ExecutionTask(self._next_id, proposal, tt)
        self._next_id += 1
        return t

    def add_execution_proposals(
        self, proposals: list[ExecutionProposal], context: dict | None = None
    ) -> list[ExecutionTask]:
        """Split proposals into typed tasks (reference addExecutionProposals:63)."""
        all_tasks = []
        for p in proposals:
            if p.has_replica_action:
                all_tasks.append(self._task(p, TaskType.INTER_BROKER_REPLICA_ACTION))
            elif p.disk_moves:
                all_tasks.append(self._task(p, TaskType.INTRA_BROKER_REPLICA_ACTION))
            if p.has_leader_action:
                # leadership settles in phase 2 via preferred-leader election,
                # after any replica move of the same partition completed
                # (reference runs moveLeaderships after interBrokerMoveReplicas,
                # Executor.java:749)
                all_tasks.append(self._task(p, TaskType.LEADER_ACTION))
        self._inter += [t for t in all_tasks if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION]
        self._intra += [t for t in all_tasks if t.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION]
        self._leadership += [t for t in all_tasks if t.task_type == TaskType.LEADER_ACTION]
        self._inter = self.strategy.order(self._inter, context)
        return all_tasks

    def adopt_tasks(self, tasks: list[ExecutionTask], context: dict | None = None):
        """Re-queue PRE-BUILT tasks (journal recovery): ids are preserved —
        a recovered task must journal under the id it started with — and
        the id counter jumps past them so later additions cannot collide."""
        for t in tasks:
            self._next_id = max(self._next_id, t.execution_id + 1)
        self._inter += [t for t in tasks if t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION]
        self._intra += [t for t in tasks if t.task_type == TaskType.INTRA_BROKER_REPLICA_ACTION]
        self._leadership += [t for t in tasks if t.task_type == TaskType.LEADER_ACTION]
        self._inter = self.strategy.order(self._inter, context)

    # ------------------------------------------------------------------

    @property
    def remaining_inter_broker_moves(self) -> list[ExecutionTask]:
        return list(self._inter)

    @property
    def remaining_intra_broker_moves(self) -> list[ExecutionTask]:
        return list(self._intra)

    @property
    def remaining_leadership_moves(self) -> list[ExecutionTask]:
        return list(self._leadership)

    def get_leadership_movement_tasks(self, num_tasks: int) -> list[ExecutionTask]:
        """Reference getLeadershipMovementTasks:295."""
        out, self._leadership = self._leadership[:num_tasks], self._leadership[num_tasks:]
        return out

    def get_intra_broker_replica_movement_tasks(
        self, ready_brokers: dict[int, int], max_total: int | None = None
    ) -> list[ExecutionTask]:
        out = []
        rest = []
        for t in self._intra:
            # slots are charged on the brokers actually COPYING between
            # logdirs (one per disk move), not the replica list
            brokers = {b for (b, _old, _new) in t.proposal.disk_moves}
            if not brokers and t.proposal.new_replicas:
                brokers = {t.proposal.new_replicas[0]}
            if (
                brokers
                and all(ready_brokers.get(b, 0) > 0 for b in brokers)
                and (max_total is None or len(out) < max_total)
            ):
                for b in brokers:
                    ready_brokers[b] -= 1
                out.append(t)
            else:
                rest.append(t)
        self._intra = rest
        return out

    def get_inter_broker_replica_movement_tasks(
        self,
        ready_brokers: dict[int, int],
        in_progress_partitions: set[tuple[int, int]],
        max_total: int | None = None,
    ) -> list[ExecutionTask]:
        """Drain tasks whose source AND destination brokers have slots,
        round-robin across brokers so slots aren't starved
        (reference getInterBrokerReplicaMovementTasks:314).  max_total
        bounds the drain so the executor's global
        max.num.cluster.movements budget is honored."""
        slots = dict(ready_brokers)
        chosen: list[ExecutionTask] = []
        chosen_ids: set[int] = set()
        partitions_involved = set(in_progress_partitions)

        new_task_added = True
        while new_task_added and (max_total is None or len(chosen) < max_total):
            new_task_added = False
            brokers_involved: set[int] = set()
            for broker_id in list(slots):
                if max_total is not None and len(chosen) >= max_total:
                    break
                if broker_id in brokers_involved or slots.get(broker_id, 0) <= 0:
                    continue
                for t in self._inter:
                    if t.execution_id in chosen_ids:
                        continue
                    p = t.proposal
                    key = (p.topic, p.partition)
                    old, new = set(p.old_replicas), set(p.new_replicas)
                    adds = new - old
                    drops = old - new
                    involved = adds | drops
                    if broker_id not in involved:
                        continue
                    if key in partitions_involved:
                        continue
                    if any(slots.get(b, 0) <= 0 for b in involved):
                        continue
                    for b in involved:
                        slots[b] -= 1
                        brokers_involved.add(b)
                    partitions_involved.add(key)
                    chosen.append(t)
                    chosen_ids.add(t.execution_id)
                    new_task_added = True
                    break
        self._inter = [t for t in self._inter if t.execution_id not in chosen_ids]
        return chosen
