"""Durable execution journal — crash-safe record of everything the
executor does to the real cluster.

Reference: executor/Executor.java persists executor state (ongoing
execution, removed/demoted broker reservations) so a restarted process
can reconcile instead of stranding in-flight reassignments; here the
persistence is an append-only JSONL file (configurable via
`executor.journal.dir`) because there is no ZooKeeper to lean on.

Record stream per execution (one execution per file — `start_execution`
truncates, because a finished predecessor has nothing left to recover):

  {"t": "start", "uuid", "ms", "tasks": [...], "options": {...},
   "removed": {...}, "demoted": {...}}       execution begins
  {"t": "throttle_set", "rate", "topics"}    replication throttle applied
  {"t": "task", "id", "state", "ms"}         every task state transition
  {"t": "concurrency", "inter", "cluster"}   adaptive-cap change
  {"t": "reaped", "id", "mode", "ms"}        stuck-move reaper action
  {"t": "reservation", "removed", "demoted"} reservation map change
  {"t": "throttle_cleared"}                  throttle removed
  {"t": "finished", "ms", "result"}          execution completed cleanly

Writes are batched then flush+fsync'd (`executor.journal.fsync.batch.size`;
1 = every record is durable before the cluster mutation proceeds).  The
`start`, throttle, `reaped` and `finished` records always fsync — they are
the records recovery correctness depends on.  Replay tolerates a torn
final line (the crash happened mid-write): decoding stops at the first
malformed line and everything before it is trusted.  A zero-length file —
a crash between file creation and the fsync'd start record — means "no
unfinished execution", never an error.

Fencing (fleet HA, fleet/leases.py): with a `fence` attached, every
append first checks the lease (`Fence.check` raises `FencedError` for a
deposed holder — nothing is written) and stamps the live lease `epoch`
into the record.  Replay tracks a running high-water epoch: a record
whose epoch is BELOW an epoch already seen earlier in the file is a
zombie's late write that slipped in before its fence tripped, and is
ignored so it can never poison reconciliation.  Legitimate mixed epochs
(a takeover resuming its predecessor's execution appends at a higher
epoch) replay in full.

Retention: `start_execution` rotates a FINISHED predecessor into an
archive file (`<journal path>.<ms>.<id>.done`) instead of discarding
it, so the journal dir accumulates one file per terminal execution;
`prune_archives` (run during start-up reconciliation and after each
rotation, per `executor.journal.retention.{count,hours}`) deletes
terminal archives beyond the bounds and NEVER touches a file without a
`finished` record — an unfinished journal awaiting recovery is
sacrosanct.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import uuid as uuid_mod

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.tasks import ExecutionTask, TaskState, TaskType

#: record types that must be durable the moment they are appended,
#: regardless of the fsync batch size
_CRITICAL = frozenset({"start", "throttle_set", "throttle_cleared", "reaped",
                       "finished"})


def proposal_to_journal(p: ExecutionProposal) -> dict:
    """Full round-trippable proposal encoding (the REST `to_json` drops
    fields recovery needs to re-submit a move)."""
    return {
        "partition": int(p.partition),
        "topic": int(p.topic),
        "old_leader": int(p.old_leader),
        "new_leader": int(p.new_leader),
        "old_replicas": [int(b) for b in p.old_replicas],
        "new_replicas": [int(b) for b in p.new_replicas],
        "disk_moves": [[int(b), int(o), int(n)] for (b, o, n) in p.disk_moves],
        "inter": float(p.inter_broker_data_to_move),
        "intra": float(p.intra_broker_data_to_move),
    }


def proposal_from_journal(d: dict) -> ExecutionProposal:
    return ExecutionProposal(
        partition=d["partition"],
        topic=d["topic"],
        old_leader=d["old_leader"],
        new_leader=d["new_leader"],
        old_replicas=tuple(d["old_replicas"]),
        new_replicas=tuple(d["new_replicas"]),
        disk_moves=tuple((b, o, n) for b, o, n in d.get("disk_moves", ())),
        inter_broker_data_to_move=d.get("inter", 0.0),
        intra_broker_data_to_move=d.get("intra", 0.0),
    )


def task_to_journal(task: ExecutionTask, key: tuple[str, int]) -> dict:
    return {
        "id": int(task.execution_id),
        "type": task.task_type.value,
        "key": [key[0], int(key[1])],
        "proposal": proposal_to_journal(task.proposal),
    }


def task_from_journal(d: dict) -> tuple[ExecutionTask, tuple[str, int]]:
    task = ExecutionTask(
        execution_id=d["id"],
        proposal=proposal_from_journal(d["proposal"]),
        task_type=TaskType(d["type"]),
    )
    return task, (d["key"][0], d["key"][1])


class ExecutionJournal:
    """Append-only JSONL journal with fsync'd batches.

    Thread-safe: the execution loop, the reaper and mid-execution admin
    calls may append concurrently.
    """

    def __init__(self, path: str, *, fsync_batch: int = 1, fence=None,
                 retention_count: int | None = None,
                 retention_hours: float | None = None):
        """fence: fleet/leases.py Fence (or None outside fleet HA) — every
        append checks it (FencedError for a deposed holder) and stamps its
        epoch into the record.  retention_count/retention_hours bound the
        archived terminal journals prune_archives() keeps."""
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.fsync_batch = max(1, int(fsync_batch))
        self.fence = fence
        self.retention_count = retention_count
        self.retention_hours = retention_hours
        self._lock = threading.Lock()
        self._file = None  # opened lazily in append mode
        self._pending = 0
        self.records_written = 0
        self.fsyncs = 0

    # ------------------------------------------------------------- write

    def _ensure_open(self):
        if self._file is None:
            # appending after a crash-torn tail would glue the new record
            # onto the partial line and poison every record after it —
            # truncate back to the last fully-valid record first
            self._repair_torn_tail()
            self._file = open(self.path, "a", encoding="utf-8")  # noqa: SIM115

    def _repair_torn_tail(self):
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn final line
            s = line.strip()
            if s:
                try:
                    rec = json.loads(s)
                except ValueError:
                    break
                if not isinstance(rec, dict) or "t" not in rec:
                    break
            good += len(line)
        if good < len(data):
            with open(self.path, "rb+") as f:
                f.truncate(good)

    def append(self, record: dict) -> None:
        if self.fence is not None:
            # the fence check happens BEFORE anything touches the file: a
            # deposed holder's append raises FencedError and writes nothing;
            # the live epoch is stamped so replay can spot any write that
            # still raced the handover (prefix high-water filter)
            record = dict(record, epoch=self.fence.check(op="journal.append"))
        line = json.dumps(record, separators=(",", ":"))
        with self._lock:
            self._ensure_open()
            self._file.write(line + "\n")
            self._pending += 1
            self.records_written += 1
            if self._pending >= self.fsync_batch or record.get("t") in _CRITICAL:
                self._fsync_locked()

    def _fsync_locked(self):
        self._file.flush()
        os.fsync(self._file.fileno())
        self._pending = 0
        self.fsyncs += 1

    def flush(self) -> None:
        with self._lock:
            if self._file is not None and self._pending:
                self._fsync_locked()

    def start_execution(self, record: dict) -> None:
        """Begin a new execution: rotate a cleanly-FINISHED predecessor
        into a terminal archive (`<path>.<ms>.<id>.done`, pruned by
        prune_archives), truncate otherwise (an unfinished predecessor was
        already reconciled), and durably write the start record before any
        cluster mutation happens."""
        if self.fence is not None:
            # fenced BEFORE the rotation/truncation: a deposed holder must
            # not destroy the journal the new holder will reconcile from
            self.fence.check(op="journal.start")
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            rotated = self._rotate_terminal_locked()
            self._file = open(self.path, "w", encoding="utf-8")  # noqa: SIM115
            self._pending = 0
        if rotated:
            # opportunistic retention at rotation time too: a long-lived
            # process running many executions must not accumulate archives
            # unboundedly between restarts
            try:
                self.prune_archives()
            except OSError:
                pass
        self.append(dict(record, t="start"))

    def _rotate_terminal_locked(self) -> bool:
        """Archive the previous journal file IF it recorded a finished
        execution; unfinished (reconciled) or empty predecessors are
        simply overwritten, exactly as before.  True if a file rotated."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return False
        if not data or b'"t":"finished"' not in data:
            return False
        try:
            ms = int(os.path.getmtime(self.path) * 1000)
        except OSError:
            ms = 0
        archive = f"{self.path}.{ms}.{uuid_mod.uuid4().hex[:8]}.done"
        try:
            os.replace(self.path, archive)
        except OSError:
            return False  # rotation is best-effort; truncation proceeds
        return True

    def prune_archives(self, *, now_ms: int | None = None) -> int:
        """Delete terminal journal archives beyond
        `executor.journal.retention.{count,hours}`.  Runs during start-up
        reconciliation.  Only files that verifiably contain a `finished`
        record are ever removed — the live journal and anything unfinished
        (a journal awaiting recovery) are untouched.  Returns the number
        of files pruned."""
        import time as _time

        if self.retention_count is None and self.retention_hours is None:
            return 0
        d = os.path.dirname(self.path)
        base = os.path.basename(self.path) + "."
        archives: list[tuple[float, str]] = []
        try:
            names = os.listdir(d)
        except OSError:
            return 0
        for fn in names:
            if fn.startswith(base) and fn.endswith(".done"):
                p = os.path.join(d, fn)
                try:
                    archives.append((os.path.getmtime(p), p))
                except OSError:
                    continue
        archives.sort(reverse=True)  # newest first
        doomed: set[str] = set()
        if self.retention_count is not None:
            doomed.update(p for _m, p in archives[max(0, self.retention_count):])
        if self.retention_hours is not None:
            now_s = (now_ms / 1000.0) if now_ms is not None else _time.time()
            cutoff = now_s - self.retention_hours * 3600.0
            doomed.update(p for m, p in archives if m < cutoff)
        pruned = 0
        for p in doomed:
            try:
                with open(p, "rb") as f:
                    terminal = b'"t":"finished"' in f.read()
            except OSError:
                continue
            if not terminal:
                continue  # unfinished journals are never retention-pruned
            try:
                os.remove(p)
                pruned += 1
            except OSError:
                pass
        return pruned

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                if self._pending:
                    self._fsync_locked()
                self._file.close()
                self._file = None

    # -------------------------------------------------------------- read

    def replay(self) -> list[dict]:
        """Decode the journal, tolerating crash truncation: a torn final
        line (or any garbage after it) ends the replay; every record
        before it is returned.  A zero-length file (crash between file
        creation and the fsync'd start record) decodes to [].

        Fencing: records carry the writer's lease epoch (fleet HA).  A
        record whose epoch is BELOW the running high-water epoch of the
        records before it is a deposed holder's late write — dropped, so
        a zombie can never poison reconciliation.  Epoch-less records
        (single-instance deployments) always replay."""
        records: list[dict] = []
        high_water: int | None = None
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # torn tail — trust only what decoded
                    if not isinstance(rec, dict) or "t" not in rec:
                        break
                    epoch = rec.get("epoch")
                    if isinstance(epoch, int):
                        if high_water is not None and epoch < high_water:
                            continue  # fenced below high water: zombie write
                        high_water = epoch
                    records.append(rec)
        except OSError:
            return []
        return records

    def unfinished_execution(self) -> "JournaledExecution | None":
        """The in-flight execution a crashed predecessor left behind, or
        None when the journal is absent, zero-length (created but never
        started), or cleanly finished."""
        records = self.replay()
        if not records or records[0].get("t") != "start":
            return None
        if any(r.get("t") == "finished" for r in records):
            return None
        return JournaledExecution.from_records(records)


@dataclasses.dataclass
class JournaledExecution:
    """Parsed view of an unfinished journal: the last-known state of every
    task plus the side effects (throttle, reservations) still standing."""

    uuid: str | None
    options: dict
    started_ms: int
    #: execution id -> (task at its last journaled state, partition key)
    tasks: dict[int, tuple[ExecutionTask, tuple[str, int]]]
    removed: dict[int, int]  # broker id -> reservation ms
    demoted: dict[int, int]
    throttle_active: bool
    throttled_topics: list[str]
    #: last journaled adaptive caps (None = never adjusted)
    adaptive: dict | None

    @staticmethod
    def from_records(records: list[dict]) -> "JournaledExecution":
        start = records[0]
        tasks: dict[int, tuple[ExecutionTask, tuple[str, int]]] = {}
        for td in start.get("tasks", ()):
            task, key = task_from_journal(td)
            tasks[task.execution_id] = (task, key)
        removed = {int(b): int(ms) for b, ms in start.get("removed", {}).items()}
        demoted = {int(b): int(ms) for b, ms in start.get("demoted", {}).items()}
        throttle_active = False
        throttled: list[str] = []
        adaptive = None
        for rec in records[1:]:
            t = rec.get("t")
            if t == "task":
                entry = tasks.get(rec.get("id"))
                if entry is None:
                    continue
                task, _key = entry
                # replay transitions WITHOUT the state machine's validity
                # check: the journal is the authority on what happened
                task.state = TaskState(rec["state"])
                if task.state == TaskState.IN_PROGRESS:
                    task.start_time_ms = rec.get("ms", -1)
                elif task.state in (TaskState.COMPLETED, TaskState.ABORTED,
                                    TaskState.DEAD):
                    task.end_time_ms = rec.get("ms", -1)
            elif t == "throttle_set":
                throttle_active = True
                throttled = list(rec.get("topics", ()))
            elif t == "throttle_cleared":
                throttle_active = False
                throttled = []
            elif t == "reservation":
                removed = {int(b): int(ms)
                           for b, ms in rec.get("removed", {}).items()}
                demoted = {int(b): int(ms)
                           for b, ms in rec.get("demoted", {}).items()}
            elif t == "concurrency":
                adaptive = {k: rec[k] for k in ("inter", "cluster") if k in rec}
        return JournaledExecution(
            uuid=start.get("uuid"),
            options=start.get("options", {}),
            started_ms=start.get("ms", 0),
            tasks=tasks,
            removed=removed,
            demoted=demoted,
            throttle_active=throttle_active,
            throttled_topics=throttled,
            adaptive=adaptive,
        )
