"""Durable execution journal — crash-safe record of everything the
executor does to the real cluster.

Reference: executor/Executor.java persists executor state (ongoing
execution, removed/demoted broker reservations) so a restarted process
can reconcile instead of stranding in-flight reassignments; here the
persistence is an append-only JSONL file (configurable via
`executor.journal.dir`) because there is no ZooKeeper to lean on.

Record stream per execution (one execution per file — `start_execution`
truncates, because a finished predecessor has nothing left to recover):

  {"t": "start", "uuid", "ms", "tasks": [...], "options": {...},
   "removed": {...}, "demoted": {...}}       execution begins
  {"t": "throttle_set", "rate", "topics"}    replication throttle applied
  {"t": "task", "id", "state", "ms"}         every task state transition
  {"t": "concurrency", "inter", "cluster"}   adaptive-cap change
  {"t": "reaped", "id", "mode", "ms"}        stuck-move reaper action
  {"t": "reservation", "removed", "demoted"} reservation map change
  {"t": "throttle_cleared"}                  throttle removed
  {"t": "finished", "ms", "result"}          execution completed cleanly

Writes are batched then flush+fsync'd (`executor.journal.fsync.batch.size`;
1 = every record is durable before the cluster mutation proceeds).  The
`start`, throttle, `reaped` and `finished` records always fsync — they are
the records recovery correctness depends on.  Replay tolerates a torn
final line (the crash happened mid-write): decoding stops at the first
malformed line and everything before it is trusted.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor.tasks import ExecutionTask, TaskState, TaskType

#: record types that must be durable the moment they are appended,
#: regardless of the fsync batch size
_CRITICAL = frozenset({"start", "throttle_set", "throttle_cleared", "reaped",
                       "finished"})


def proposal_to_journal(p: ExecutionProposal) -> dict:
    """Full round-trippable proposal encoding (the REST `to_json` drops
    fields recovery needs to re-submit a move)."""
    return {
        "partition": int(p.partition),
        "topic": int(p.topic),
        "old_leader": int(p.old_leader),
        "new_leader": int(p.new_leader),
        "old_replicas": [int(b) for b in p.old_replicas],
        "new_replicas": [int(b) for b in p.new_replicas],
        "disk_moves": [[int(b), int(o), int(n)] for (b, o, n) in p.disk_moves],
        "inter": float(p.inter_broker_data_to_move),
        "intra": float(p.intra_broker_data_to_move),
    }


def proposal_from_journal(d: dict) -> ExecutionProposal:
    return ExecutionProposal(
        partition=d["partition"],
        topic=d["topic"],
        old_leader=d["old_leader"],
        new_leader=d["new_leader"],
        old_replicas=tuple(d["old_replicas"]),
        new_replicas=tuple(d["new_replicas"]),
        disk_moves=tuple((b, o, n) for b, o, n in d.get("disk_moves", ())),
        inter_broker_data_to_move=d.get("inter", 0.0),
        intra_broker_data_to_move=d.get("intra", 0.0),
    )


def task_to_journal(task: ExecutionTask, key: tuple[str, int]) -> dict:
    return {
        "id": int(task.execution_id),
        "type": task.task_type.value,
        "key": [key[0], int(key[1])],
        "proposal": proposal_to_journal(task.proposal),
    }


def task_from_journal(d: dict) -> tuple[ExecutionTask, tuple[str, int]]:
    task = ExecutionTask(
        execution_id=d["id"],
        proposal=proposal_from_journal(d["proposal"]),
        task_type=TaskType(d["type"]),
    )
    return task, (d["key"][0], d["key"][1])


class ExecutionJournal:
    """Append-only JSONL journal with fsync'd batches.

    Thread-safe: the execution loop, the reaper and mid-execution admin
    calls may append concurrently.
    """

    def __init__(self, path: str, *, fsync_batch: int = 1):
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.fsync_batch = max(1, int(fsync_batch))
        self._lock = threading.Lock()
        self._file = None  # opened lazily in append mode
        self._pending = 0
        self.records_written = 0
        self.fsyncs = 0

    # ------------------------------------------------------------- write

    def _ensure_open(self):
        if self._file is None:
            # appending after a crash-torn tail would glue the new record
            # onto the partial line and poison every record after it —
            # truncate back to the last fully-valid record first
            self._repair_torn_tail()
            self._file = open(self.path, "a", encoding="utf-8")  # noqa: SIM115

    def _repair_torn_tail(self):
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn final line
            s = line.strip()
            if s:
                try:
                    rec = json.loads(s)
                except ValueError:
                    break
                if not isinstance(rec, dict) or "t" not in rec:
                    break
            good += len(line)
        if good < len(data):
            with open(self.path, "rb+") as f:
                f.truncate(good)

    def append(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":"))
        with self._lock:
            self._ensure_open()
            self._file.write(line + "\n")
            self._pending += 1
            self.records_written += 1
            if self._pending >= self.fsync_batch or record.get("t") in _CRITICAL:
                self._fsync_locked()

    def _fsync_locked(self):
        self._file.flush()
        os.fsync(self._file.fileno())
        self._pending = 0
        self.fsyncs += 1

    def flush(self) -> None:
        with self._lock:
            if self._file is not None and self._pending:
                self._fsync_locked()

    def start_execution(self, record: dict) -> None:
        """Begin a new execution: truncate (the previous execution either
        finished or was already reconciled) and durably write the start
        record before any cluster mutation happens."""
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(self.path, "w", encoding="utf-8")  # noqa: SIM115
            self._pending = 0
        self.append(dict(record, t="start"))

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                if self._pending:
                    self._fsync_locked()
                self._file.close()
                self._file = None

    # -------------------------------------------------------------- read

    def replay(self) -> list[dict]:
        """Decode the journal, tolerating crash truncation: a torn final
        line (or any garbage after it) ends the replay; every record
        before it is returned."""
        records: list[dict] = []
        try:
            with open(self.path, encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break  # torn tail — trust only what decoded
                    if not isinstance(rec, dict) or "t" not in rec:
                        break
                    records.append(rec)
        except OSError:
            return []
        return records

    def unfinished_execution(self) -> "JournaledExecution | None":
        """The in-flight execution a crashed predecessor left behind, or
        None when the journal is absent/empty/cleanly finished."""
        records = self.replay()
        if not records or records[0].get("t") != "start":
            return None
        if any(r.get("t") == "finished" for r in records):
            return None
        return JournaledExecution.from_records(records)


@dataclasses.dataclass
class JournaledExecution:
    """Parsed view of an unfinished journal: the last-known state of every
    task plus the side effects (throttle, reservations) still standing."""

    uuid: str | None
    options: dict
    started_ms: int
    #: execution id -> (task at its last journaled state, partition key)
    tasks: dict[int, tuple[ExecutionTask, tuple[str, int]]]
    removed: dict[int, int]  # broker id -> reservation ms
    demoted: dict[int, int]
    throttle_active: bool
    throttled_topics: list[str]
    #: last journaled adaptive caps (None = never adjusted)
    adaptive: dict | None

    @staticmethod
    def from_records(records: list[dict]) -> "JournaledExecution":
        start = records[0]
        tasks: dict[int, tuple[ExecutionTask, tuple[str, int]]] = {}
        for td in start.get("tasks", ()):
            task, key = task_from_journal(td)
            tasks[task.execution_id] = (task, key)
        removed = {int(b): int(ms) for b, ms in start.get("removed", {}).items()}
        demoted = {int(b): int(ms) for b, ms in start.get("demoted", {}).items()}
        throttle_active = False
        throttled: list[str] = []
        adaptive = None
        for rec in records[1:]:
            t = rec.get("t")
            if t == "task":
                entry = tasks.get(rec.get("id"))
                if entry is None:
                    continue
                task, _key = entry
                # replay transitions WITHOUT the state machine's validity
                # check: the journal is the authority on what happened
                task.state = TaskState(rec["state"])
                if task.state == TaskState.IN_PROGRESS:
                    task.start_time_ms = rec.get("ms", -1)
                elif task.state in (TaskState.COMPLETED, TaskState.ABORTED,
                                    TaskState.DEAD):
                    task.end_time_ms = rec.get("ms", -1)
            elif t == "throttle_set":
                throttle_active = True
                throttled = list(rec.get("topics", ()))
            elif t == "throttle_cleared":
                throttle_active = False
                throttled = []
            elif t == "reservation":
                removed = {int(b): int(ms)
                           for b, ms in rec.get("removed", {}).items()}
                demoted = {int(b): int(ms)
                           for b, ms in rec.get("demoted", {}).items()}
            elif t == "concurrency":
                adaptive = {k: rec[k] for k in ("inter", "cluster") if k in rec}
        return JournaledExecution(
            uuid=start.get("uuid"),
            options=start.get("options", {}),
            started_ms=start.get("ms", 0),
            tasks=tasks,
            removed=removed,
            demoted=demoted,
            throttle_active=throttle_active,
            throttled_topics=throttled,
            adaptive=adaptive,
        )
