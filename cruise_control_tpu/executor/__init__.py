"""Executor layer: proposal execution with throttling and progress tracking.

Reference: cruise-control/.../executor/ (Executor.java, ExecutionTaskPlanner.java,
strategy/, ReplicationThrottleHelper.java) + the Scala ZK bridge
(ExecutorUtils.scala), replaced by the ClusterAdmin SPI.
"""

from cruise_control_tpu.executor.admin import (
    ClusterAdmin,
    LeadershipSpec,
    ReassignmentSpec,
    SimulatedClusterAdmin,
)
from cruise_control_tpu.executor.executor import (
    ConcurrencyAdjuster,
    ExecutionOptions,
    ExecutionResult,
    Executor,
    ExecutorState,
    NoOngoingExecutionError,
    OngoingExecutionError,
)
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategy import (
    STRATEGIES_BY_NAME,
    BaseReplicaMovementStrategy,
    PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy,
    ReplicaMovementStrategy,
)
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskTracker,
    TaskState,
    TaskType,
)
from cruise_control_tpu.executor.throttle import ReplicationThrottleHelper
