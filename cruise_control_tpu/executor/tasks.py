"""Execution task state machine + tracker.

Reference: executor/ExecutionTask.java:26-40 (PENDING -> IN_PROGRESS ->
{COMPLETED, ABORTING -> ABORTED, DEAD}) and executor/ExecutionTaskTracker.java:25.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable

from cruise_control_tpu.analyzer.proposals import ExecutionProposal


class TaskType(enum.Enum):
    """Reference ExecutionTask.TaskType."""

    INTER_BROKER_REPLICA_ACTION = "INTER_BROKER_REPLICA_ACTION"
    INTRA_BROKER_REPLICA_ACTION = "INTRA_BROKER_REPLICA_ACTION"
    LEADER_ACTION = "LEADER_ACTION"


class TaskState(enum.Enum):
    PENDING = "PENDING"
    IN_PROGRESS = "IN_PROGRESS"
    ABORTING = "ABORTING"
    ABORTED = "ABORTED"
    COMPLETED = "COMPLETED"
    DEAD = "DEAD"


_VALID_TRANSFER = {
    TaskState.PENDING: {TaskState.IN_PROGRESS},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD, TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.COMPLETED: set(),
    TaskState.DEAD: set(),
    TaskState.ABORTED: set(),
}


@dataclasses.dataclass
class ExecutionTask:
    """One unit of execution (reference executor/ExecutionTask.java:44)."""

    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: int = -1
    end_time_ms: int = -1
    alert_time_ms: int = -1
    #: called with (task, new_state, now_ms) after every transition — the
    #: executor's durable-journal hook (executor/journal.py); excluded from
    #: equality/repr so tasks stay value-comparable in tests
    observer: Callable | None = dataclasses.field(
        default=None, repr=False, compare=False
    )

    def _transfer(self, target: TaskState, now_ms: int):
        if target not in _VALID_TRANSFER[self.state]:
            raise ValueError(f"invalid task transition {self.state} -> {target}")
        self.state = target
        if target == TaskState.IN_PROGRESS:
            self.start_time_ms = now_ms
        if target in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD):
            self.end_time_ms = now_ms
        if self.observer is not None:
            self.observer(self, target, now_ms)

    def in_progress(self, now_ms: int):
        self._transfer(TaskState.IN_PROGRESS, now_ms)

    def completed(self, now_ms: int):
        self._transfer(TaskState.COMPLETED, now_ms)

    def aborting(self, now_ms: int):
        self._transfer(TaskState.ABORTING, now_ms)

    def aborted(self, now_ms: int):
        self._transfer(TaskState.ABORTED, now_ms)

    def kill(self, now_ms: int):
        self._transfer(TaskState.DEAD, now_ms)

    @property
    def active(self) -> bool:
        return self.state in (TaskState.IN_PROGRESS, TaskState.ABORTING)

    def to_json(self) -> dict:
        return {
            "executionId": self.execution_id,
            "type": self.task_type.value,
            "state": self.state.value,
            "proposal": self.proposal.to_json(),
        }


class ExecutionTaskTracker:
    """Counts tasks by (type, state) + data-movement progress
    (reference executor/ExecutionTaskTracker.java:25).

    observer: installed on every tracked task (see ExecutionTask.observer)."""

    def __init__(self, observer: Callable | None = None):
        self._tasks: dict[int, ExecutionTask] = {}
        self._observer = observer

    def add(self, task: ExecutionTask):
        if self._observer is not None:
            task.observer = self._observer
        self._tasks[task.execution_id] = task

    def tasks(self, task_type: TaskType | None = None, state: TaskState | None = None):
        return [
            t
            for t in self._tasks.values()
            if (task_type is None or t.task_type == task_type)
            and (state is None or t.state == state)
        ]

    def count(self, task_type: TaskType | None = None, state: TaskState | None = None) -> int:
        return len(self.tasks(task_type, state))

    @property
    def finished(self) -> bool:
        return all(
            t.state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD)
            for t in self._tasks.values()
        )

    def in_execution_data_bytes(self) -> float:
        return sum(
            t.proposal.inter_broker_data_to_move
            for t in self._tasks.values()
            if t.state == TaskState.IN_PROGRESS
            and t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION
        )

    def finished_data_bytes(self) -> float:
        return sum(
            t.proposal.inter_broker_data_to_move
            for t in self._tasks.values()
            if t.state in (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD)
            and t.task_type == TaskType.INTER_BROKER_REPLICA_ACTION
        )

    def status(self) -> dict:
        out: dict = {}
        for tt in TaskType:
            out[tt.value] = {
                st.value: self.count(tt, st) for st in TaskState if self.count(tt, st)
            }
        return out
