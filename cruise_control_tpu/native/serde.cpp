// Native batch deserializer for the metrics-reporter wire stream.
//
// The reference ingests metric records on the JVM inside each broker and in
// the service's sampler loop (CruiseControlMetricsReporterSampler.java:101
// poll loop; MetricSampleAggregator.addSample is called millions of times
// per window at LinkedIn scale — SURVEY §3.2 hot loop).  Our service-side
// analog is transport.poll() + a per-record Python loop: object-per-record
// allocation dominates.  This translation unit parses a whole framed batch
// in one pass into columnar arrays (and interns topic names), so the Python
// side works with numpy vectors instead of record objects.
//
// Record layout (little-endian, reporter/metrics.py MetricSerde):
//   class u8 | version u8 | metric_type u16 | time_ms i64 | broker i32 |
//   value f64  [| topic_len u16 | topic bytes  [| partition i32 ]]
// Batch framing: u32 record length before each record.
//
// Build: g++ -O3 -shared -fPIC serde.cpp -o _ccnative.so   (see __init__.py)

#include <cstdint>
#include <cstring>
#include <string_view>
#include <unordered_map>

extern "C" {

// Returns the number of records parsed, or a negative error:
//   -1 malformed frame/record, -2 record capacity exceeded,
//   -3 topic-table capacity exceeded.
// topic_offsets/topic_lens describe each interned topic as a slice of the
// INPUT buffer (first occurrence); topic_ids[i] indexes that table (-1 for
// broker-scope records).  partitions[i] is -1 unless class==2.
long ccn_batch_deserialize(
    const uint8_t* buf, long n,
    uint8_t* class_ids, uint16_t* mtypes, int64_t* times, int32_t* brokers,
    double* values, int32_t* partitions, int32_t* topic_ids,
    int64_t* topic_offsets, int32_t* topic_lens, long max_topics,
    long* n_topics_out, long max_records) {
  std::unordered_map<std::string_view, int32_t> interned;
  interned.reserve(256);
  long count = 0;
  long off = 0;
  while (off + 4 <= n) {
    uint32_t rec_len;
    std::memcpy(&rec_len, buf + off, 4);
    off += 4;
    if (rec_len < 24 || off + (long)rec_len > n) return -1;
    if (count >= max_records) return -2;
    const uint8_t* r = buf + off;
    uint8_t cls = r[0];  // r[1] = version; all current versions share layout
    uint16_t mt;
    std::memcpy(&mt, r + 2, 2);
    int64_t tms;
    std::memcpy(&tms, r + 4, 8);
    int32_t bid;
    std::memcpy(&bid, r + 12, 4);
    double val;
    std::memcpy(&val, r + 16, 8);
    int32_t tid = -1;
    int32_t part = -1;
    if (cls != 0) {
      if (rec_len < 26) return -1;
      uint16_t tl;
      std::memcpy(&tl, r + 24, 2);
      if (26u + tl > rec_len) return -1;
      std::string_view topic(reinterpret_cast<const char*>(r + 26), tl);
      auto it = interned.find(topic);
      if (it == interned.end()) {
        if ((long)interned.size() >= max_topics) return -3;
        tid = (int32_t)interned.size();
        interned.emplace(topic, tid);
        topic_offsets[tid] = off + 26;
        topic_lens[tid] = tl;
      } else {
        tid = it->second;
      }
      if (cls == 2) {
        if (26u + tl + 4u > rec_len) return -1;
        std::memcpy(&part, r + 26 + tl, 4);
      }
    }
    class_ids[count] = cls;
    mtypes[count] = mt;
    times[count] = tms;
    brokers[count] = bid;
    values[count] = val;
    partitions[count] = part;
    topic_ids[count] = tid;
    ++count;
    off += rec_len;
  }
  if (off != n) return -1;  // trailing garbage
  *n_topics_out = (long)interned.size();
  return count;
}

// CRC-32C (Castagnoli) — the Kafka record-batch checksum.  Slice-by-8
// table walk; the Python fallback's per-byte loop is ~100x slower on the
// multi-MB fetch payloads the metrics consumer verifies every poll.
static uint32_t kCrcTable[8][256];
static bool kCrcInit = [] {
  for (uint32_t n = 0; n < 256; ++n) {
    uint32_t c = n;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0x82F63B78u ^ (c >> 1) : c >> 1;
    kCrcTable[0][n] = c;
  }
  for (uint32_t n = 0; n < 256; ++n) {
    uint32_t c = kCrcTable[0][n];
    for (int s = 1; s < 8; ++s) {
      c = kCrcTable[0][c & 0xFF] ^ (c >> 8);
      kCrcTable[s][n] = c;
    }
  }
  return true;
}();

uint32_t ccn_crc32c(const uint8_t* buf, long n, uint32_t crc) {
  (void)kCrcInit;
  crc = ~crc;
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, buf, 8);
    w ^= crc;  // little-endian host assumed (x86/ARM LE)
    crc = kCrcTable[7][w & 0xFF] ^ kCrcTable[6][(w >> 8) & 0xFF] ^
          kCrcTable[5][(w >> 16) & 0xFF] ^ kCrcTable[4][(w >> 24) & 0xFF] ^
          kCrcTable[3][(w >> 32) & 0xFF] ^ kCrcTable[2][(w >> 40) & 0xFF] ^
          kCrcTable[1][(w >> 48) & 0xFF] ^ kCrcTable[0][(w >> 56) & 0xFF];
    buf += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *buf++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

}  // extern "C"
