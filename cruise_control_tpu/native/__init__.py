"""Native (C++) host-runtime components, ctypes-loaded.

The TPU compute path is JAX/XLA; the host runtime around it keeps Python
out of per-record hot loops with small C++ kernels:

  serde.cpp — one-pass columnar batch deserialization of the
  metrics-reporter wire stream with topic interning (the service-side
  analog of the reference's JVM sampler loop,
  CruiseControlMetricsReporterSampler.java:101).

The shared library is built on demand with g++ (cached next to the
sources); every entry point has a pure-Python fallback so the framework
stays functional without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "serde.cpp")
_LIB = os.path.join(_DIR, "_ccnative.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_lib_failed = False


def _load() -> ctypes.CDLL | None:
    """Build (if stale/missing) and load the shared library; None if the
    toolchain is unavailable."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if (
                not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC,
                     "-o", _LIB + ".tmp"],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(_LIB + ".tmp", _LIB)
            lib = ctypes.CDLL(_LIB)
            fn = lib.ccn_batch_deserialize
            fn.restype = ctypes.c_long
            fn.argtypes = [
                ctypes.c_char_p, ctypes.c_long,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_long, ctypes.POINTER(ctypes.c_long), ctypes.c_long,
            ]
            crc = lib.ccn_crc32c
            crc.restype = ctypes.c_uint32
            crc.argtypes = [ctypes.c_char_p, ctypes.c_long, ctypes.c_uint32]
            _lib = lib
        except (OSError, subprocess.SubprocessError):
            _lib_failed = True
        return _lib


def native_available() -> bool:
    return _load() is not None


def crc32c_native(data: bytes, crc: int = 0) -> int | None:
    """Hardware-speed CRC-32C, or None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.ccn_crc32c(data, len(data), crc))


class MetricBatch:
    """Columnar view of a deserialized metric batch."""

    __slots__ = (
        "class_ids", "metric_types", "times_ms", "broker_ids", "values",
        "partitions", "topic_ids", "topics",
    )

    def __init__(self, class_ids, metric_types, times_ms, broker_ids, values,
                 partitions, topic_ids, topics):
        self.class_ids = class_ids      # u8[N] 0=broker 1=topic 2=partition
        self.metric_types = metric_types  # u16[N]
        self.times_ms = times_ms        # i64[N]
        self.broker_ids = broker_ids    # i32[N]
        self.values = values            # f64[N]
        self.partitions = partitions    # i32[N], -1 for non-partition records
        self.topic_ids = topic_ids      # i32[N], -1 for broker records
        self.topics = topics            # list[str], indexed by topic_ids

    def __len__(self) -> int:
        return len(self.values)


def frame_records(records: list[bytes]) -> bytes:
    """u32-length-prefixed concatenation (the batch wire framing)."""
    out = bytearray()
    for r in records:
        out += len(r).to_bytes(4, "little")
        out += r
    return bytes(out)


def batch_deserialize(framed: bytes, *, force_python: bool = False) -> MetricBatch:
    """Parse a framed record batch into columns (native, else Python)."""
    lib = None if force_python else _load()
    if lib is None:
        return _batch_deserialize_py(framed)
    n = len(framed)
    max_records = max(1, n // 28)  # 24B head + 4B frame minimum
    max_topics = max(16, max_records)
    class_ids = np.empty(max_records, np.uint8)
    mtypes = np.empty(max_records, np.uint16)
    times = np.empty(max_records, np.int64)
    brokers = np.empty(max_records, np.int32)
    values = np.empty(max_records, np.float64)
    partitions = np.empty(max_records, np.int32)
    topic_ids = np.empty(max_records, np.int32)
    topic_offsets = np.empty(max_topics, np.int64)
    topic_lens = np.empty(max_topics, np.int32)
    n_topics = ctypes.c_long(0)
    count = lib.ccn_batch_deserialize(
        framed, n,
        class_ids.ctypes.data, mtypes.ctypes.data, times.ctypes.data,
        brokers.ctypes.data, values.ctypes.data, partitions.ctypes.data,
        topic_ids.ctypes.data, topic_offsets.ctypes.data,
        topic_lens.ctypes.data, max_topics, ctypes.byref(n_topics), max_records,
    )
    if count < 0:
        raise ValueError(f"malformed metric batch (native rc={count})")
    topics = [
        framed[topic_offsets[i]: topic_offsets[i] + topic_lens[i]].decode()
        for i in range(n_topics.value)
    ]
    return MetricBatch(
        class_ids[:count], mtypes[:count], times[:count], brokers[:count],
        values[:count], partitions[:count], topic_ids[:count], topics,
    )


def _batch_deserialize_py(framed: bytes) -> MetricBatch:
    """Pure-Python fallback with identical semantics."""
    import struct

    head = struct.Struct("<BBHqid")
    off = 0
    n = len(framed)
    cols: list[tuple] = []
    topics: list[str] = []
    interned: dict[str, int] = {}
    while off + 4 <= n:
        (rec_len,) = struct.unpack_from("<I", framed, off)
        off += 4
        if rec_len < 24 or off + rec_len > n:
            raise ValueError("malformed metric batch")
        cls, _ver, mt, tms, bid, val = head.unpack_from(framed, off)
        tid, part = -1, -1
        if cls != 0:
            # mirror the native decoder's bounds checks (serde.cpp returns -1)
            if rec_len < 26:
                raise ValueError("malformed metric batch")
            (tl,) = struct.unpack_from("<H", framed, off + 24)
            if 26 + tl > rec_len or (cls == 2 and 26 + tl + 4 > rec_len):
                raise ValueError("malformed metric batch")
            topic = framed[off + 26: off + 26 + tl].decode()
            tid = interned.get(topic)
            if tid is None:
                tid = interned[topic] = len(topics)
                topics.append(topic)
            if cls == 2:
                (part,) = struct.unpack_from("<i", framed, off + 26 + tl)
        cols.append((cls, mt, tms, bid, val, part, tid))
        off += rec_len
    if off != n:
        raise ValueError("malformed metric batch")
    if not cols:
        z = np.zeros(0)
        return MetricBatch(
            z.astype(np.uint8), z.astype(np.uint16), z.astype(np.int64),
            z.astype(np.int32), z.astype(np.float64), z.astype(np.int32),
            z.astype(np.int32), [],
        )
    arr = list(zip(*cols))
    return MetricBatch(
        np.asarray(arr[0], np.uint8), np.asarray(arr[1], np.uint16),
        np.asarray(arr[2], np.int64), np.asarray(arr[3], np.int32),
        np.asarray(arr[4], np.float64), np.asarray(arr[5], np.int32),
        np.asarray(arr[6], np.int32), topics,
    )
