"""Partition-spec rules for sharding the flattened model over MODEL_AXIS.

The flattened model (models/state.ClusterState inside
analyzer/engine.EngineStatics) has three families of leaves:

  * replica/partition-indexed   — O(R) / O(P) rows: placements, loads,
    id columns, the partition->replica member table, the per-partition
    rack census.  These are the memory at north-star scale (25k brokers
    / 2M partitions => ~5M replica rows) and the arrays the sharded
    mesh mode splits over MODEL_AXIS.
  * broker/host/disk-indexed    — O(B) rows, thousands; replicated.
  * scalars / tiny metadata     — replicated.

`match_partition_rules` is the classic pjit-era helper (SNIPPETS.md
[1]-[3]): an ordered (regex, PartitionSpec) table matched against the
"/"-joined key path of every leaf, first match wins.  The tables below
are the single source of truth consumed by parallel/mesh.py both for
`jax.device_put` placement (wrapped into NamedSharding) and for the
`shard_map` in/out specs of the device programs.
"""

from __future__ import annotations

import dataclasses
import re

import jax
from jax.sharding import PartitionSpec as P

from cruise_control_tpu.models.state import ClusterShape


def _path_str(path) -> str:
    parts = []
    for k in path:
        name = getattr(k, "name", None)
        if name is None:
            name = getattr(k, "key", None)
        if name is None:
            name = getattr(k, "idx", None)
        parts.append(str(name))
    return "/".join(parts)


def match_partition_rules(rules, tree):
    """Map every leaf of `tree` to the PartitionSpec of the first rule
    whose regex `search`es its "/"-joined key path; unmatched leaves get
    the replicated spec `P()`.  Returns a same-structure pytree of
    specs."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = []
    for path, _leaf in flat:
        name = _path_str(path)
        spec = P()
        for pat, rule_spec in rules:
            if re.search(pat, name):
                spec = rule_spec
                break
        specs.append(spec)
    return jax.tree_util.tree_unflatten(treedef, specs)


def statics_partition_rules(model_axis: str):
    """EngineStatics leaf -> spec: replica-row and partition-row leaves
    shard over the model axis, broker/disk/host/scalar leaves replicate."""
    return (
        (r"state/replica_", P(model_axis)),
        (r"(^|/)part_replicas$", P(model_axis)),
        (r".", P()),
    )


def carry_partition_rules(restart_axis: str, model_axis: str):
    """EngineCarry leaf -> spec with the leading per-restart block axis:
    mutable replica placements and the partition rack census shard over
    the model axis; broker aggregates and the PRNG key replicate across
    it (every shard applies every accepted move's broker-side update)."""
    return (
        (r"(^|/)replica_(broker|is_leader|disk)$", P(restart_axis, model_axis)),
        (r"(^|/)part_rack_count$", P(restart_axis, model_axis)),
        (r".", P(restart_axis)),
    )


def shard_multiple_shape(shape: ClusterShape, n: int) -> ClusterShape:
    """Round the replica and partition axes of an (already bucketed)
    shape up to multiples of `n` so every MODEL_AXIS shard holds an
    equal contiguous block.  Other axes are untouched — broker/topic/
    rack/host leaves stay replicated."""
    if n <= 1:
        return shape

    def up(v: int) -> int:
        return ((int(v) + n - 1) // n) * n

    return dataclasses.replace(
        shape,
        num_replicas=up(shape.num_replicas),
        num_partitions=up(shape.num_partitions),
    )
