"""Array-encoded cluster workload state — the TPU-native ClusterModel.

The reference models a cluster as a mutable object graph
Rack -> Host -> Broker -> Disk -> Replica with windowed Load objects
(reference: model/ClusterModel.java:48, model/Replica.java, model/Load.java).
Goals then pointer-chase that graph in a single-threaded greedy loop.

Here the same information is flattened into fixed-shape device arrays so that
goal scores are segment-reductions and candidate moves are gather/scatter
deltas — evaluable for thousands of plans in parallel under vmap/jit.

Encoding (R = padded replica count, B = broker count, D = max disks/broker):

  replica axis [R]:
    replica_broker     i32  current broker id (padding rows point at broker 0
                            but are masked out by replica_valid everywhere)
    replica_partition  i32  global partition id
    replica_topic      i32  topic id of the partition
    replica_pos        i32  position in the partition's replica list (0 =
                            preferred leader; reference model/Partition.java)
    replica_is_leader  bool currently the partition leader
    replica_valid      bool padding mask
    replica_orig_broker i32 broker at model-build time (immigrant tracking,
                            reference model/Replica.java originalBroker)
    replica_offline    bool on a dead broker / bad disk; must be relocated
    replica_disk       i32  disk index within broker (JBOD), 0 if single-disk
    replica_load_leader   f32[R, 4]  expected utilization if this replica
                                     leads its partition
    replica_load_follower f32[R, 4]  expected utilization as a follower
                                     (NW_OUT = 0; CPU = follower share —
                                     reference model/ModelUtils.java:53-67)

  broker axis [B]:
    broker_capacity    f32[B, 4]  per-resource capacity (DISK = sum of disks)
    broker_rack        i32        rack id
    broker_host        i32        host id
    broker_alive       bool       live broker (dead => replicas offline)
    broker_new         bool       newly-added broker (only immigrant replicas
                                  allowed — reference analyzer semantics)
    broker_valid       bool       padding mask
    disk_capacity      f32[B, D]  per-logdir capacity (JBOD)
    disk_alive         bool[B, D] logdir health

Static (non-array) metadata lives in the companion `ClusterShape` so the
pytree leaves are all arrays and jit retraces only when shapes change.

Leadership semantics: the effective load of a replica is
`where(is_leader, load_leader, load_follower)`; relocating leadership between
two replicas of a partition therefore shifts CPU/NW_OUT between their brokers
exactly like reference model/ClusterModel.java:374 (relocateLeadership).
Potential-NW-out (reference model/ClusterModel.java:70,205) is the sum of
`replica_load_leader[:, NW_OUT]` over a broker's replicas — what the broker
would serve if it led everything it hosts.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES


@dataclasses.dataclass(frozen=True)
class ClusterShape:
    """Static shape/topology metadata for a ClusterState.

    Kept out of the pytree so it can gate jit specialization explicitly.
    """

    num_replicas: int  # padded R
    num_brokers: int  # B
    num_partitions: int  # P
    num_topics: int
    num_racks: int
    num_hosts: int
    max_disks_per_broker: int  # D

    @property
    def R(self) -> int:  # noqa: N802 — math-style aliases
        return self.num_replicas

    @property
    def B(self) -> int:  # noqa: N802
        return self.num_brokers

    @property
    def P(self) -> int:  # noqa: N802
        return self.num_partitions


@dataclasses.dataclass(frozen=True)
class ShapeBucketPolicy:
    """Geometric shape-bucketing policy for engine-cache stability.

    Engines compile per exact ClusterShape (analyzer/engine.py); a Kafka
    cluster creates partitions and adds brokers continuously, so an exact
    shape key makes nearly every model generation under churn a compile
    miss.  Rounding each axis up to the next bucket of the geometric
    series floor·growth^k (the batch/sequence-length bucketing of
    inference serving) makes successive generations land in the SAME
    padded shape: `Engine.rebind()` swaps in the fresh data with zero
    recompilation, and only a bucket overflow (≥ growth× accumulated
    churn) pays a compile.

    The padding this introduces is masked everywhere — `replica_valid`
    for replicas, `broker_valid` for brokers (never alive, zero capacity,
    never a destination, excluded from every goal denominator), and
    shape-only padding for partitions/topics/racks/hosts (no replicas
    reference them) — pinned by the exact-vs-bucketed parity tests.
    """

    enabled: bool = True
    #: bucket growth factor between adjacent buckets (> 1)
    growth: float = 1.25
    #: smallest bucket; also the series base
    floor: int = 8

    def __post_init__(self):
        if self.growth <= 1.0:
            raise ValueError(f"bucket growth must be > 1, got {self.growth}")
        if self.floor < 1:
            raise ValueError(f"bucket floor must be >= 1, got {self.floor}")

    def bucket(self, n: int) -> int:
        """Smallest bucket >= n in the series ceil(floor * growth^k)."""
        if not self.enabled:
            return int(n)
        if n <= self.floor:
            return self.floor
        import math

        # float log gets within one step of the right k; walk to the exact
        # smallest bucket so the series is deterministic and monotone
        k = max(0, int(math.log(n / self.floor) / math.log(self.growth)) - 1)
        b = int(math.ceil(self.floor * self.growth**k))
        while b < n:
            k += 1
            b = int(math.ceil(self.floor * self.growth**k))
        return b

    def bucket_shape(self, shape: ClusterShape) -> ClusterShape:
        """Round every churn-prone axis up to its bucket (D stays exact:
        logdir counts change only on hardware refresh)."""
        if not self.enabled:
            return shape
        return ClusterShape(
            num_replicas=self.bucket(shape.num_replicas),
            num_brokers=self.bucket(shape.num_brokers),
            num_partitions=self.bucket(shape.num_partitions),
            num_topics=self.bucket(shape.num_topics),
            num_racks=self.bucket(shape.num_racks),
            num_hosts=self.bucket(shape.num_hosts),
            max_disks_per_broker=shape.max_disks_per_broker,
        )

    def next_bucket_shape(self, shape: ClusterShape) -> ClusterShape:
        """The shape one partition-churn overflow lands in: the replica and
        partition axes bumped past their current bucket (other axes — topic,
        broker, rack, host — stay at their current bucket; their churn is an
        order of magnitude rarer than partition creates).  Used by the
        service's precompute loop to pre-warm the next engine so a bucket
        overflow hits a warm compile instead of a cold one."""
        return ClusterShape(
            num_replicas=self.bucket(self.bucket(shape.num_replicas) + 1),
            num_brokers=self.bucket(shape.num_brokers),
            num_partitions=self.bucket(self.bucket(shape.num_partitions) + 1),
            num_topics=self.bucket(shape.num_topics),
            num_racks=self.bucket(shape.num_racks),
            num_hosts=self.bucket(shape.num_hosts),
            max_disks_per_broker=shape.max_disks_per_broker,
        )


#: service-default policy (config keys tpu.shape.bucket.*)
DEFAULT_BUCKET_POLICY = ShapeBucketPolicy()


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "replica_broker",
        "replica_partition",
        "replica_topic",
        "replica_pos",
        "replica_is_leader",
        "replica_valid",
        "replica_orig_broker",
        "replica_offline",
        "replica_disk",
        "replica_load_leader",
        "replica_load_follower",
        "broker_capacity",
        "broker_rack",
        "broker_host",
        "broker_alive",
        "broker_new",
        "broker_valid",
        "disk_capacity",
        "disk_alive",
    ],
    meta_fields=["shape"],
)
@dataclasses.dataclass(frozen=True)
class ClusterState:
    # --- replica axis [R] ---
    replica_broker: jax.Array
    replica_partition: jax.Array
    replica_topic: jax.Array
    replica_pos: jax.Array
    replica_is_leader: jax.Array
    replica_valid: jax.Array
    replica_orig_broker: jax.Array
    replica_offline: jax.Array
    replica_disk: jax.Array
    replica_load_leader: jax.Array  # [R, NUM_RESOURCES]
    replica_load_follower: jax.Array  # [R, NUM_RESOURCES]
    # --- broker axis [B] ---
    broker_capacity: jax.Array  # [B, NUM_RESOURCES]
    broker_rack: jax.Array
    broker_host: jax.Array
    broker_alive: jax.Array
    broker_new: jax.Array
    broker_valid: jax.Array
    disk_capacity: jax.Array  # [B, D]
    disk_alive: jax.Array  # [B, D]
    # --- static metadata ---
    shape: ClusterShape

    # ---- derived quantities (cheap, jit-friendly) ----

    @property
    def replica_load(self) -> jax.Array:
        """Effective [R, 4] utilization given current leadership."""
        lead = self.replica_is_leader[:, None]
        load = jnp.where(lead, self.replica_load_leader, self.replica_load_follower)
        return jnp.where(self.replica_valid[:, None], load, 0.0)

    def broker_segment_ids(self) -> jax.Array:
        """Replica→broker ids with padding routed to an overflow bucket B."""
        return jnp.where(self.replica_broker >= 0, self.replica_broker, self.shape.B)

    def with_replicas_moved(
        self, replica_idx: jax.Array, new_broker: jax.Array, new_disk: jax.Array | None = None
    ) -> "ClusterState":
        """Scatter-update replica placement (reference ClusterModel.relocateReplica:347)."""
        rb = self.replica_broker.at[replica_idx].set(new_broker)
        disk = (
            self.replica_disk.at[replica_idx].set(new_disk)
            if new_disk is not None
            else self.replica_disk.at[replica_idx].set(0)
        )
        # offline tracks destination health, not a blanket clear: landing on a
        # dead broker/logdir keeps the replica offline
        dest_ok = self.broker_alive[new_broker] & self.disk_alive[new_broker, disk[replica_idx]]
        off = self.replica_offline.at[replica_idx].set(~dest_ok)
        return dataclasses.replace(self, replica_broker=rb, replica_offline=off, replica_disk=disk)

    def with_leadership_moved(self, from_replica: jax.Array, to_replica: jax.Array) -> "ClusterState":
        """Transfer leadership between two replicas of the same partition
        (reference ClusterModel.relocateLeadership:374)."""
        lead = self.replica_is_leader.at[from_replica].set(False).at[to_replica].set(True)
        return dataclasses.replace(self, replica_is_leader=lead)


#: check names for validate_on_device's count vector, in order
DEVICE_CHECKS = (
    "broker ids out of range",
    "replica on invalid broker",
    "partitions without exactly one leader",
    "duplicate replica of a partition on one broker",
    "non-finite or negative leader loads",
)


@jax.jit
def validate_on_device(state: ClusterState):
    """The same invariants as validate(), computed ON DEVICE and returned
    as a tiny [5] violation-count vector — on a tunneled TPU the host
    validate()'s bulk device->host transfer costs more than the checks.
    Decode nonzero entries against DEVICE_CHECKS (then re-run the host
    validate for the detailed message)."""
    valid = state.replica_valid
    B, P, R = state.shape.B, state.shape.P, state.shape.R
    brk = jnp.where(valid, state.replica_broker, 0)
    part = jnp.where(valid, state.replica_partition, 0)
    lead = state.replica_is_leader & valid

    in_range = (state.replica_broker >= 0) & (state.replica_broker < B)
    n_oor = jnp.sum(valid & ~in_range)
    n_invalid_broker = jnp.sum(valid & in_range & ~state.broker_valid[brk])

    leaders_per_part = jnp.zeros(P, jnp.int32).at[part].add(lead.astype(jnp.int32))
    present = jnp.zeros(P, jnp.bool_).at[part].max(valid)
    n_bad_leader = jnp.sum(present & (leaders_per_part != 1))

    # duplicate (partition, broker): lexsort the PAIR and compare adjacent —
    # a combined part*B+brk key would need int64, which jax truncates to
    # int32 without x64 mode (overflow at ~800k partitions x 2600 brokers)
    part_key = jnp.where(valid, part, P)  # padding sorts to the end
    brk_key = jnp.where(valid, brk, -1)
    order = jnp.lexsort((brk_key, part_key))
    ps, bs, vs = part_key[order], brk_key[order], valid[order]
    n_dup = jnp.sum((ps[1:] == ps[:-1]) & (bs[1:] == bs[:-1]) & vs[1:] & vs[:-1])

    loads = jnp.where(valid[:, None], state.replica_load_leader, 0.0)
    n_bad_load = jnp.sum(~jnp.isfinite(loads)) + jnp.sum(loads < 0)

    return jnp.stack(
        [n_oor, n_invalid_broker, n_bad_leader, n_dup, n_bad_load]
    ).astype(jnp.int32)


def validate(state: ClusterState, *, strict: bool = True) -> list[str]:
    """Host-side structural sanity check (reference ClusterModel.sanityCheck:1081).

    Checks (on materialized numpy copies — not for use inside jit):
      * exactly one leader per partition (over valid replicas)
      * replica broker ids within range and pointing at valid brokers
      * no duplicate (partition, broker) placement
      * loads are non-negative and finite
    Returns a list of human-readable problems; raises if strict and non-empty.

    Hot paths use validate_on_device instead (a [5] count vector, no bulk
    device->host transfer) and fall back here for the detailed message.
    """
    problems: list[str] = []
    # one batched device->host transfer (per-array np.asarray syncs five times)
    valid, part, brk, lead, load_l = jax.device_get(
        (
            state.replica_valid,
            state.replica_partition,
            state.replica_broker,
            state.replica_is_leader,
            state.replica_load_leader,
        )
    )
    part, brk, lead = part[valid], brk[valid], lead[valid]
    B, P = state.shape.B, state.shape.P

    if brk.size:
        in_range = (brk >= 0) & (brk < B)
        if not in_range.all():
            problems.append(f"replica broker ids out of range [0,{B}): {brk.min()}..{brk.max()}")
        bvalid = np.asarray(state.broker_valid)
        if not bvalid[brk[in_range]].all():
            problems.append("replica placed on invalid (padding) broker")

    leaders_per_part = np.bincount(part[lead], minlength=P)
    present = np.bincount(part, minlength=P) > 0
    bad = present & (leaders_per_part != 1)
    if bad.any():
        problems.append(f"{int(bad.sum())} partitions without exactly one leader")

    pb = part.astype(np.int64) * B + brk.astype(np.int64)
    if np.unique(pb).size != pb.size:
        problems.append("duplicate replica of a partition on one broker")

    loads = load_l[valid]
    if not np.isfinite(loads).all() or (loads < 0).any():
        problems.append("non-finite or negative leader loads")

    if problems and strict:
        raise ValueError("ClusterState sanity check failed: " + "; ".join(problems))
    return problems
