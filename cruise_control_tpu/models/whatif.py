"""What-if mutation primitives over the flattened ClusterState arrays.

The scenario planner (cruise_control_tpu/planner/) evaluates hypothetical
futures — lose a rack, add three brokers, double a topic's traffic —
without touching the live cluster.  Every hypothetical is expressible as
a host-side edit of the SAME padded arrays the optimizer already
consumes, so a mutated state rides the exact engine/goal machinery of a
real model generation (no parallel "simulation model" to drift).

The editing model: `HostState.of(state)` pulls every churn-prone array
to host in ONE batched device_get (the pad_state / build_statics
transfer discipline), the edit functions below mutate the numpy copies,
and `HostState.to_state()` re-materializes a ClusterState of the same
shape.  Broker ADDS consume `broker_valid=False` padding rows that
ShapeBucketPolicy already reserves — so N scenarios of one base cluster
keep one ClusterShape and share one compiled engine; only a scenario
batch that outgrows the padding pays a shape bump (planner.scenario
plans the shared shape up front).

Nothing here runs on device or inside jit; planning edits are
control-plane rare and numpy-cheap.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models.state import ClusterShape, ClusterState

#: ClusterState fields a what-if edit may touch, in declaration order
_REPLICA_FIELDS = (
    "replica_broker", "replica_partition", "replica_topic", "replica_pos",
    "replica_is_leader", "replica_valid", "replica_orig_broker",
    "replica_offline", "replica_disk", "replica_load_leader",
    "replica_load_follower",
)
_BROKER_FIELDS = (
    "broker_capacity", "broker_rack", "broker_host", "broker_alive",
    "broker_new", "broker_valid", "disk_capacity", "disk_alive",
)


@dataclasses.dataclass
class HostState:
    """Mutable host-side (numpy) copy of one ClusterState's arrays.

    Mutators record which fields they touched (`dirty`); `to_state`
    re-materializes ONLY those, so every untouched field of every
    scenario state IS the base state's device array (same object).  The
    batched evaluator exploits that aliasing: shared fields ride into the
    device program once instead of being stacked N times — for a typical
    scenario batch the stacked payload shrinks from the whole model to a
    few broker-axis vectors.
    """

    shape: ClusterShape
    arrays: dict  # field name -> np.ndarray (writable copies)
    dirty: set = dataclasses.field(default_factory=set)

    @staticmethod
    def of(state: ClusterState) -> "HostState":
        import jax

        fields = _REPLICA_FIELDS + _BROKER_FIELDS
        # one batched transfer; .copy() because device_get may alias a
        # cached host buffer and the whole point is to mutate freely
        host = jax.device_get(tuple(getattr(state, f) for f in fields))
        return HostState(
            shape=state.shape,
            arrays={f: np.array(a, copy=True) for f, a in zip(fields, host)},
        )

    def __getitem__(self, name: str) -> np.ndarray:
        return self.arrays[name]

    def touch(self, *names: str) -> np.ndarray | None:
        """Mark fields as mutated; returns the first's array for writing."""
        self.dirty.update(names)
        return self.arrays[names[0]] if names else None

    def to_state(self, base: ClusterState) -> ClusterState:
        """Re-materialize a ClusterState (same shape as `base`); only the
        mutated fields become new arrays — the rest alias `base`'s."""
        import jax.numpy as jnp

        kw = {f: jnp.asarray(self.arrays[f]) for f in sorted(self.dirty)}
        return dataclasses.replace(base, **kw) if kw else base

    # ------------------------------------------------------------------
    # derived views
    # ------------------------------------------------------------------

    def real_broker_count(self) -> int:
        return int(self["broker_valid"].sum())

    def real_rack_count(self) -> int:
        bv = self["broker_valid"]
        return int(self["broker_rack"][bv].max()) + 1 if bv.any() else 0

    def real_host_count(self) -> int:
        bv = self["broker_valid"]
        return int(self["broker_host"][bv].max()) + 1 if bv.any() else 0

    def alive_mask(self) -> np.ndarray:
        return self["broker_valid"] & self["broker_alive"]

    # ------------------------------------------------------------------
    # topology edits
    # ------------------------------------------------------------------

    def add_broker(
        self,
        *,
        rack_id: int,
        host_id: int | None = None,
        capacity: np.ndarray | None = None,
        disk_capacities: np.ndarray | None = None,
    ) -> int:
        """Activate one padding row as a live NEW broker; returns its id.

        Raises when no padding row is left (the caller planned the shared
        shape too tight) or when rack/host ids exceed the shape's axes —
        the rack axis sizes the engine's [P, num_racks] rack-count table,
        so an out-of-range id would silently corrupt rack-awareness.
        """
        bv = self["broker_valid"]
        free = np.nonzero(~bv)[0]
        if free.size == 0:
            raise ValueError(
                f"no padding broker rows left in shape B={self.shape.B}; "
                "plan the scenario batch shape with room for broker adds"
            )
        b = int(free[0])
        self.touch(
            "broker_valid", "broker_alive", "broker_new", "broker_rack",
            "broker_host", "broker_capacity", "disk_capacity", "disk_alive",
        )
        if not 0 <= rack_id < self.shape.num_racks:
            raise ValueError(
                f"rack id {rack_id} outside shape num_racks={self.shape.num_racks}"
            )
        if host_id is None:
            host_id = self.real_host_count()
        if not 0 <= host_id < self.shape.num_hosts:
            raise ValueError(
                f"host id {host_id} outside shape num_hosts={self.shape.num_hosts}"
            )
        if capacity is None:
            capacity = default_capacity_profile(self)
        cap = np.asarray(capacity, np.float32)
        self["broker_valid"][b] = True
        self["broker_alive"][b] = True
        self["broker_new"][b] = True
        self["broker_rack"][b] = rack_id
        self["broker_host"][b] = host_id
        dc = self["disk_capacity"]
        da = self["disk_alive"]
        if disk_capacities is not None:
            disks = np.asarray(disk_capacities, np.float32)
            if disks.size > dc.shape[1]:
                raise ValueError(
                    f"{disks.size} logdirs exceed shape max_disks_per_broker="
                    f"{dc.shape[1]}"
                )
            dc[b, : disks.size] = disks
            da[b, : disks.size] = True
            cap = cap.copy()
            cap[Resource.DISK] = float(disks.sum())
        else:
            dc[b, 0] = cap[Resource.DISK]
            da[b, 0] = True
        self["broker_capacity"][b] = cap
        return b

    def kill_brokers(self, broker_ids) -> None:
        """Mark brokers dead; their replicas become offline (the exact
        semantics of the facade's remove-broker model edit)."""
        ids = [int(b) for b in broker_ids]
        if not ids:
            return
        bv = self["broker_valid"]
        unknown = [b for b in ids if not (0 <= b < bv.size and bv[b])]
        if unknown:
            raise ValueError(f"broker ids {unknown} are not in the cluster model")
        self.touch("broker_alive", "replica_offline")
        self["broker_alive"][ids] = False
        on_dead = np.isin(self["replica_broker"], ids)
        self["replica_offline"][:] = (
            self["replica_offline"] | on_dead
        ) & self["replica_valid"]

    def kill_racks(self, rack_ids) -> list[int]:
        """Kill every broker on the given racks; returns the broker ids."""
        rids = {int(r) for r in rack_ids}
        bv = self["broker_valid"]
        victims = [
            int(b) for b in np.nonzero(bv)[0] if int(self["broker_rack"][b]) in rids
        ]
        self.kill_brokers(victims)
        return victims

    def demote_brokers(self, broker_ids) -> int:
        """Move leadership off the given brokers onto the lowest-position
        alive replica elsewhere (PreferredLeaderElectionGoal semantics);
        returns the number of leaderships moved.  Partitions with no
        eligible replica keep their leader (the executor would fail the
        election the same way)."""
        demoted = {int(b) for b in broker_ids}
        if not demoted:
            return 0
        valid = self["replica_valid"]
        lead = self["replica_is_leader"]
        brk = self["replica_broker"]
        part = self["replica_partition"]
        pos = self["replica_pos"]
        alive = self.alive_mask()
        self.touch("replica_is_leader")
        moved = 0
        on_demoted = valid & lead & np.isin(brk, list(demoted))
        for p in np.unique(part[on_demoted]):
            rows = np.nonzero(valid & (part == p))[0]
            rows = rows[np.argsort(pos[rows])]
            cands = [
                r for r in rows
                if int(brk[r]) not in demoted and alive[brk[r]]
            ]
            if not cands:
                continue
            lead[rows] = False
            lead[cands[0]] = True
            moved += 1
        return moved

    # ------------------------------------------------------------------
    # load edits
    # ------------------------------------------------------------------

    def scale_topic_load(self, topic_id: int, factors) -> None:
        """Scale a topic's per-replica loads; `factors` is a scalar or a
        per-resource [4] vector."""
        f = np.broadcast_to(
            np.asarray(factors, np.float32), (NUM_RESOURCES,)
        )
        self.touch("replica_load_leader", "replica_load_follower")
        rows = self["replica_valid"] & (self["replica_topic"] == int(topic_id))
        self["replica_load_leader"][rows] *= f
        self["replica_load_follower"][rows] *= f

    def scale_all_load(self, factor) -> None:
        f = np.broadcast_to(np.asarray(factor, np.float32), (NUM_RESOURCES,))
        self.touch("replica_load_leader", "replica_load_follower")
        rows = self["replica_valid"]
        self["replica_load_leader"][rows] *= f
        self["replica_load_follower"][rows] *= f

    def add_load_delta(self, delta) -> None:
        """Add an absolute per-resource [4] delta to every valid replica's
        leader load (clipped at 0).  Followers receive only the NW_IN and
        DISK components (replication traffic and storage track the leader;
        follower CPU stays modeled, follower NW_OUT stays 0 — the
        invariant the builder establishes)."""
        d = np.asarray(delta, np.float32).reshape(NUM_RESOURCES)
        self.touch("replica_load_leader", "replica_load_follower")
        rows = self["replica_valid"]
        ll = self["replica_load_leader"]
        fl = self["replica_load_follower"]
        ll[rows] = np.maximum(ll[rows] + d, 0.0)
        fd = np.zeros(NUM_RESOURCES, np.float32)
        fd[Resource.NW_IN] = d[Resource.NW_IN]
        fd[Resource.DISK] = d[Resource.DISK]
        fl[rows] = np.maximum(fl[rows] + fd, 0.0)


# ----------------------------------------------------------------------
# live-state primitives (streaming controller)
# ----------------------------------------------------------------------


def _round_up_pow2(n: int, floor: int = 64) -> int:
    n = max(int(n), 1)
    b = floor
    while b < n:
        b <<= 1
    return b


class LiveState:
    """Device-resident flattened ClusterState + IN-PLACE delta primitives.

    Where HostState serves the planner's hypothetical futures (host copy,
    mutate, re-materialize a scenario state), LiveState is the streaming
    controller's (controller/streaming.py) view of the REAL cluster: the
    padded arrays stay on device across metric windows and each window
    roll scatters only the changed cells into them — donated buffers, the
    same trick as the fused anneal, so no full model re-flatten happens
    while the shape bucket holds.

    Ownership contract: each update DONATES exactly the arrays it
    rewrites (never the whole pytree — XLA's buffer reuse across a
    donated set may re-book a pass-through buffer for a different
    same-shape output, scribbling arrays other references still read).
    Donation still invalidates the previous Array objects of the
    rewritten leaves, so the controller is the state's sole owner —
    anything it published earlier (an OptimizerResult's state_before
    rides these arrays) must be consumed through host-side fields
    (summary, proposals) only.  The facade honors this by parking its
    bucket-prewarm path while the controller runs.

    Scatter index vectors are padded to power-of-two buckets with the
    out-of-range sentinel (dropped by the scatter), so successive windows
    of different delta sizes reuse one compiled program.
    """

    def __init__(self, state: ClusterState):
        self.state = state

    @property
    def shape(self) -> ClusterShape:
        return self.state.shape

    def set_partition_loads(
        self, rows: np.ndarray, leader_loads: np.ndarray,
        follower_loads: np.ndarray,
    ) -> int:
        """Scatter new ABSOLUTE per-replica loads (leader + follower
        variants) into the live arrays; rows are replica indices.  Returns
        the padded scatter width (observability: the compiled-program
        bucket this window landed in)."""
        import jax.numpy as jnp

        R = self.state.shape.R
        n = int(len(rows))
        width = _round_up_pow2(max(n, 1))
        pad = width - n
        rows = np.concatenate([np.asarray(rows, np.int32), np.full(pad, R, np.int32)])
        ll = np.concatenate(
            [np.asarray(leader_loads, np.float32),
             np.zeros((pad, NUM_RESOURCES), np.float32)]
        )
        fl = np.concatenate(
            [np.asarray(follower_loads, np.float32),
             np.zeros((pad, NUM_RESOURCES), np.float32)]
        )
        st = self.state
        from cruise_control_tpu.common.dispatch import count_dispatch

        count_dispatch("livestate.scatter")
        new_ll, new_fl = _scatter_partition_loads(
            st.replica_load_leader, st.replica_load_follower,
            jnp.asarray(rows), jnp.asarray(ll), jnp.asarray(fl),
        )
        import dataclasses as _dc

        self.state = _dc.replace(
            st, replica_load_leader=new_ll, replica_load_follower=new_fl
        )
        return width

    def adopt_loads(self, ll, fl) -> None:
        """Adopt already-scattered load arrays as the live ones — the fused
        streaming cycle's hand-back: the cycle program DONATED the previous
        live arrays and returned the rescattered pair, so ownership simply
        transfers (no device work, no copies)."""
        import dataclasses as _dc

        self.state = _dc.replace(
            self.state, replica_load_leader=ll, replica_load_follower=fl
        )

    def set_broker_liveness(self, alive: np.ndarray) -> None:
        """Replace the broker_alive vector in place and re-derive
        replica_offline from it (a broker death/revival between windows is
        a topology delta that needs no re-flatten)."""
        import dataclasses as _dc

        import jax.numpy as jnp

        st = self.state
        alive = jnp.asarray(alive, bool)
        from cruise_control_tpu.common.dispatch import count_dispatch

        count_dispatch("livestate.liveness")
        off = _with_broker_alive(
            st.replica_broker, st.replica_disk, st.replica_offline,
            st.replica_valid, st.disk_alive, alive,
        )
        self.state = _dc.replace(st, broker_alive=alive, replica_offline=off)


def _make_scatter_partition_loads():
    """Donate ONLY the two arrays being rewritten.  Donating the whole
    state pytree is tempting but wrong: the untouched leaves would pass
    through as donated identity outputs, and XLA's buffer reuse across a
    donated set can re-book a pass-through buffer for a different
    same-shape output — scribbling placement arrays other live references
    (the published result, the warm-start placement) still read."""
    from functools import partial as _partial

    import jax

    @_partial(jax.jit, donate_argnums=(0, 1))
    def fn(ll, fl, rows, new_ll, new_fl):
        drop = dict(mode="drop")
        return ll.at[rows].set(new_ll, **drop), fl.at[rows].set(new_fl, **drop)

    return fn


def _make_with_broker_alive():
    """replica_offline is rewritten (donated); broker_alive is replaced
    by the new vector outright, everything else is untouched."""
    from functools import partial as _partial

    import jax

    @_partial(jax.jit, donate_argnums=(2,))
    def fn(rb, rd, offline, valid, disk_alive, alive):
        off = valid & ~(alive[rb] & disk_alive[rb, rd])
        return off

    return fn


class _Lazy:
    """Deferred jitted-program construction: importing this module must
    not touch jax (the planner imports it host-side only)."""

    def __init__(self, make):
        self._make = make
        self._fn = None

    def __call__(self, *args):
        if self._fn is None:
            self._fn = self._make()
        return self._fn(*args)


_scatter_partition_loads = _Lazy(_make_scatter_partition_loads)
_with_broker_alive = _Lazy(_make_with_broker_alive)


def default_capacity_profile(h: HostState) -> np.ndarray:
    """Capacity for an added broker with no explicit profile: the
    per-resource MEDIAN over live brokers — the honest 'another one like
    the ones we have' assumption (robust to one outsized broker)."""
    alive = h.alive_mask()
    if not alive.any():
        alive = h["broker_valid"]
    if not alive.any():
        return np.asarray([100.0, 1e5, 1e5, 1e6], np.float32)
    return np.median(h["broker_capacity"][alive], axis=0).astype(np.float32)
