from cruise_control_tpu.models.aggregates import BrokerAggregates, compute_aggregates, host_load
from cruise_control_tpu.models.builder import (
    BrokerSpec,
    ClusterModelBuilder,
    PartitionSpec,
    default_follower_load,
    pad_state,
)
from cruise_control_tpu.models.state import (
    DEFAULT_BUCKET_POLICY,
    ClusterShape,
    ClusterState,
    ShapeBucketPolicy,
    validate,
)
from cruise_control_tpu.models.stats import ClusterStats, compute_stats

__all__ = [
    "BrokerAggregates",
    "BrokerSpec",
    "ClusterModelBuilder",
    "ClusterShape",
    "ClusterState",
    "ClusterStats",
    "DEFAULT_BUCKET_POLICY",
    "PartitionSpec",
    "ShapeBucketPolicy",
    "compute_aggregates",
    "compute_stats",
    "default_follower_load",
    "host_load",
    "pad_state",
    "validate",
]
