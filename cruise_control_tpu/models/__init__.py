from cruise_control_tpu.models.aggregates import BrokerAggregates, compute_aggregates, host_load
from cruise_control_tpu.models.builder import (
    BrokerSpec,
    ClusterModelBuilder,
    PartitionSpec,
    default_follower_load,
)
from cruise_control_tpu.models.state import ClusterShape, ClusterState, validate
from cruise_control_tpu.models.stats import ClusterStats, compute_stats

__all__ = [
    "BrokerAggregates",
    "BrokerSpec",
    "ClusterModelBuilder",
    "ClusterShape",
    "ClusterState",
    "ClusterStats",
    "PartitionSpec",
    "compute_aggregates",
    "compute_stats",
    "default_follower_load",
    "host_load",
    "validate",
]
