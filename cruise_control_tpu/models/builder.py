"""Host-side builder: topology + per-partition loads → padded ClusterState.

Plays the role of reference model/ClusterModel.java's mutating creation API
(createRack:892, createBroker:867, createReplica:768, setReplicaLoad:684):
the monitor layer feeds it brokers/partitions, it emits immutable device
arrays.  Padding to a static replica capacity keeps jit shapes stable across
model generations (pad-and-mask, SURVEY §7 hard part (c)).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models.state import ClusterShape, ClusterState, ShapeBucketPolicy


@dataclasses.dataclass
class BrokerSpec:
    broker_id: int
    rack: str
    host: str | None = None  # defaults to one host per broker
    capacity: np.ndarray | None = None  # [4]; DISK overridden by disk sum if disks given
    disk_capacities: list[float] | None = None  # JBOD logdir capacities
    alive: bool = True
    new_broker: bool = False
    bad_disks: list[int] | None = None


@dataclasses.dataclass(frozen=True)
class ClusterCatalog:
    """Host-side id <-> name mappings for one built ClusterState.

    The array model only carries dense integer ids; everything that talks
    to the outside world (executor, REST responses, logs) resolves names
    through this catalog (role of the reference's TopicPartition objects).
    """

    topics: tuple[str, ...]  # topic name by topic id
    partitions: tuple[tuple[str, int], ...]  # (topic name, partition number) by global pid
    racks: tuple[str, ...] = ()
    hosts: tuple[str, ...] = ()

    def __post_init__(self):
        # name -> id dict built ONCE: topic_id() is called per stored sample
        # by the sample-store boundary (kafka/sample_store.py topic_id_fn)
        # and an O(T) tuple.index scan per call is quadratic over a store
        # replay (frozen dataclass: bypass the setattr guard)
        object.__setattr__(
            self, "_topic_idx", {t: i for i, t in enumerate(self.topics)}
        )

    def topic_id(self, name: str) -> int:
        return self._topic_idx[name]

    def partition_key(self, pid: int) -> tuple[str, int]:
        return self.partitions[pid]

    def topic_names_by_id(self) -> dict[int, str]:
        return dict(enumerate(self.topics))


@dataclasses.dataclass
class PartitionSpec:
    topic: str
    partition: int
    replica_brokers: list[int]  # first entry = current leader
    leader_load: np.ndarray  # [4] utilization when leading
    follower_load: np.ndarray | None = None  # [4]; default derives from leader_load
    replica_disks: list[int] | None = None
    leader_pos: int = 0  # index into replica_brokers of the current leader


def default_follower_load(leader_load: np.ndarray, follower_cpu_fraction: float = 0.3) -> np.ndarray:
    """Follower load derived from leader load.

    NW_OUT drops to 0 (only leaders serve consumer fetch), CPU drops to the
    follower share (reference model/ModelUtils.getFollowerCpuUtilFromLeaderLoad:53-67
    derives follower CPU from leader byte rates; we model it as a configured
    fraction until the linear-regression estimator lands in the monitor layer),
    NW_IN and DISK are identical (replication traffic and storage).
    """
    f = np.array(leader_load, dtype=np.float32).copy()
    f[Resource.NW_OUT] = 0.0
    f[Resource.CPU] = leader_load[Resource.CPU] * follower_cpu_fraction
    return f


@dataclasses.dataclass
class _BrokerArrays:
    """Shared broker-level arrays for both build paths."""

    racks: list[str]
    hosts: list[str]
    D: int
    capacity: np.ndarray  # [B, 4]
    rack: np.ndarray  # int32 [B]
    host: np.ndarray  # int32 [B]
    alive: np.ndarray  # bool [B]
    new: np.ndarray  # bool [B]
    disk_capacity: np.ndarray  # [B, D]
    disk_alive: np.ndarray  # bool [B, D]


def _broker_arrays(brokers: list[BrokerSpec]) -> _BrokerArrays:
    """Dense-id check + per-broker capacity/rack/host/disk population —
    the single source both ClusterModelBuilder.build and
    build_state_columnar assemble brokers from."""
    brokers = sorted(brokers, key=lambda b: b.broker_id)
    ids = [b.broker_id for b in brokers]
    if ids != list(range(len(ids))):
        raise ValueError(f"broker ids must be dense 0..B-1, got {ids}")
    B = len(brokers)
    racks = sorted({b.rack for b in brokers})
    rack_idx = {r: i for i, r in enumerate(racks)}
    hosts = sorted({b.host if b.host is not None else f"__host_{b.broker_id}" for b in brokers})
    host_idx = {h: i for i, h in enumerate(hosts)}

    D = max((len(b.disk_capacities) for b in brokers if b.disk_capacities), default=1)
    out = _BrokerArrays(
        racks=racks,
        hosts=hosts,
        D=D,
        capacity=np.zeros((B, NUM_RESOURCES), np.float32),
        rack=np.zeros(B, np.int32),
        host=np.zeros(B, np.int32),
        alive=np.zeros(B, bool),
        new=np.zeros(B, bool),
        disk_capacity=np.zeros((B, D), np.float32),
        disk_alive=np.zeros((B, D), bool),
    )
    for i, b in enumerate(brokers):
        cap = np.asarray(
            b.capacity if b.capacity is not None else [100.0, 1e5, 1e5, 1e6], np.float32
        )
        if b.disk_capacities:
            dc = np.asarray(b.disk_capacities, np.float32)
            out.disk_capacity[i, : len(dc)] = dc
            out.disk_alive[i, : len(dc)] = True
            cap = cap.copy()
            cap[Resource.DISK] = dc.sum()
        else:
            out.disk_capacity[i, 0] = cap[Resource.DISK]
            out.disk_alive[i, 0] = True
        for bad in b.bad_disks or []:
            out.disk_alive[i, bad] = False
        out.capacity[i] = cap
        out.rack[i] = rack_idx[b.rack]
        out.host[i] = host_idx[b.host if b.host is not None else f"__host_{b.broker_id}"]
        out.alive[i] = b.alive
        out.new[i] = b.new_broker
    return out


def _pad_rows(a: np.ndarray, n: int) -> np.ndarray:
    """Zero/False-pad the leading axis of `a` out to n rows."""
    if a.shape[0] == n:
        return a
    out = np.zeros((n,) + a.shape[1:], a.dtype)
    out[: a.shape[0]] = a
    return out


def _assemble_state(
    ba: _BrokerArrays,
    shape: ClusterShape,
    r_broker, r_part, r_topic, r_pos, r_leader, r_valid, r_offline, r_disk,
    r_ll, r_fl,
) -> ClusterState:
    import jax.numpy as jnp

    # shape may be a BUCKETED superset of the data (ShapeBucketPolicy):
    # replica rows beyond the allocation and broker rows beyond the real
    # broker count become padding — broker_valid=False brokers are never
    # alive, carry zero capacity, and are masked out of every goal
    # denominator and candidate-destination set downstream.
    B_real = ba.capacity.shape[0]
    B = shape.B
    broker_valid = np.zeros(B, bool)
    broker_valid[:B_real] = True
    D = shape.max_disks_per_broker
    return ClusterState(
        replica_broker=jnp.asarray(_pad_rows(r_broker, shape.R)),
        replica_partition=jnp.asarray(_pad_rows(r_part, shape.R)),
        replica_topic=jnp.asarray(_pad_rows(r_topic, shape.R)),
        replica_pos=jnp.asarray(_pad_rows(r_pos, shape.R)),
        replica_is_leader=jnp.asarray(_pad_rows(r_leader, shape.R)),
        replica_valid=jnp.asarray(_pad_rows(r_valid, shape.R)),
        replica_orig_broker=jnp.asarray(_pad_rows(r_broker.copy(), shape.R)),
        replica_offline=jnp.asarray(_pad_rows(r_offline, shape.R)),
        replica_disk=jnp.asarray(_pad_rows(r_disk, shape.R)),
        replica_load_leader=jnp.asarray(_pad_rows(r_ll, shape.R)),
        replica_load_follower=jnp.asarray(_pad_rows(r_fl, shape.R)),
        broker_capacity=jnp.asarray(_pad_rows(ba.capacity, B)),
        broker_rack=jnp.asarray(_pad_rows(ba.rack, B)),
        broker_host=jnp.asarray(_pad_rows(ba.host, B)),
        broker_alive=jnp.asarray(_pad_rows(ba.alive, B)),
        broker_new=jnp.asarray(_pad_rows(ba.new, B)),
        broker_valid=jnp.asarray(broker_valid),
        disk_capacity=jnp.asarray(_pad_rows(ba.disk_capacity, B)[:, :D]),
        disk_alive=jnp.asarray(_pad_rows(ba.disk_alive, B)[:, :D]),
        shape=shape,
    )


def build_state_columnar(
    brokers: list[BrokerSpec],
    cols,
    leader_load: np.ndarray,
    follower_load: np.ndarray,
    *,
    replica_capacity: int | None = None,
    bucket_policy: ShapeBucketPolicy | None = None,
) -> tuple[ClusterState, ClusterCatalog]:
    """Vectorized twin of ClusterModelBuilder.build for monitor-shaped input.

    cols: a monitor.topology.TopologyColumns (array-encoded partition list);
    leader_load / follower_load: float32 [P, 4] in cols' partition order.
    Replica-level population is pure numpy (no per-replica Python), which is
    what keeps model generation sub-second at reference scale — the role of
    the reference's bulk setReplicaLoad path (model/ClusterModel.java:684)
    under its cluster-model-creation timer.  Output is identical (same
    ordering, catalog, and arrays) to feeding the same data through
    ClusterModelBuilder one PartitionSpec at a time.
    """
    ba = _broker_arrays(brokers)
    B = ba.capacity.shape[0]
    broker_alive = ba.alive
    disk_alive = ba.disk_alive

    # partitions sorted by (topic name, partition number) — the builder's
    # canonical order.  topic ids in cols are first-seen; rank them by name.
    T = len(cols.topic_names)
    by_name = sorted(range(T), key=lambda i: cols.topic_names[i])
    topics_sorted = [cols.topic_names[i] for i in by_name]
    rank_of_tid = np.empty(T, np.int32)
    rank_of_tid[by_name] = np.arange(T, dtype=np.int32)
    part_rank = rank_of_tid[cols.part_topic]
    order = np.lexsort((cols.part_num, part_rank))
    P = order.size

    counts_o = cols.replica_counts[order].astype(np.int64)
    total = int(counts_o.sum())
    R = replica_capacity or total
    if R < total:
        raise ValueError(f"replica_capacity {R} < actual replicas {total}")

    # gather each sorted partition's replica segment from the flat array
    seg_start = np.repeat(cols.replica_offsets[order], counts_o)
    new_off = np.concatenate(([0], np.cumsum(counts_o)))
    within = np.arange(total, dtype=np.int64) - np.repeat(new_off[:-1], counts_o)
    src = seg_start + within

    r_broker = np.zeros(R, np.int32)
    r_part = np.zeros(R, np.int32)
    r_topic = np.zeros(R, np.int32)
    r_pos = np.zeros(R, np.int32)
    r_leader = np.zeros(R, bool)
    r_valid = np.zeros(R, bool)
    r_offline = np.zeros(R, bool)
    r_disk = np.zeros(R, np.int32)
    r_ll = np.zeros((R, NUM_RESOURCES), np.float32)
    r_fl = np.zeros((R, NUM_RESOURCES), np.float32)

    r_broker[:total] = cols.replica_broker[src]
    r_part[:total] = np.repeat(np.arange(P, dtype=np.int32), counts_o)
    r_topic[:total] = np.repeat(part_rank[order], counts_o)
    r_pos[:total] = within
    r_leader[:total] = within == np.repeat(
        cols.part_leader_pos[order].astype(np.int64), counts_o
    )
    r_valid[:total] = True
    r_offline[:total] = (
        ~broker_alive[r_broker[:total]]
        | ~disk_alive[r_broker[:total], 0]  # monitor places replicas on disk 0
    )
    ll_sorted = np.asarray(leader_load, np.float32)[order]
    fl_sorted = np.asarray(follower_load, np.float32)[order]
    r_ll[:total] = np.repeat(ll_sorted, counts_o, axis=0)
    r_fl[:total] = np.repeat(fl_sorted, counts_o, axis=0)

    names_by_part = [cols.topic_names[t] for t in cols.part_topic[order]]
    catalog = ClusterCatalog(
        topics=tuple(topics_sorted),
        partitions=tuple(zip(names_by_part, cols.part_num[order].tolist())),
        racks=tuple(ba.racks),
        hosts=tuple(ba.hosts),
    )
    shape = ClusterShape(
        num_replicas=R,
        num_brokers=B,
        num_partitions=P,
        num_topics=max(len(topics_sorted), 1),
        num_racks=max(len(ba.racks), 1),
        num_hosts=max(len(ba.hosts), 1),
        max_disks_per_broker=ba.D,
    )
    if bucket_policy is not None:
        shape = bucket_policy.bucket_shape(shape)
    state = _assemble_state(
        ba, shape,
        r_broker, r_part, r_topic, r_pos, r_leader, r_valid, r_offline, r_disk,
        r_ll, r_fl,
    )
    return state, catalog


def pad_state(state: ClusterState, shape: ClusterShape) -> ClusterState:
    """Pad an already-built ClusterState out to a (bucketed) superset shape.

    Replica/broker rows beyond the current shape become masked padding
    (replica_valid / broker_valid False); partition/topic/rack/host axes
    grow shape-only (no replica references them).  Used by the service's
    next-bucket engine pre-warm and by the exact-vs-bucketed parity tests.
    """
    s = state.shape
    if shape == s:
        return state
    for f in dataclasses.fields(ClusterShape):
        if getattr(shape, f.name) < getattr(s, f.name):
            raise ValueError(f"pad_state cannot shrink {f.name}: {s} -> {shape}")
    import jax
    import jax.numpy as jnp

    repl_fields = [
        "replica_broker", "replica_partition", "replica_topic", "replica_pos",
        "replica_is_leader", "replica_valid", "replica_orig_broker",
        "replica_offline", "replica_disk", "replica_load_leader",
        "replica_load_follower",
    ]
    brk_fields = [
        "broker_capacity", "broker_rack", "broker_host", "broker_alive",
        "broker_new", "broker_valid", "disk_capacity", "disk_alive",
    ]
    host = dict(zip(
        repl_fields + brk_fields,
        jax.device_get(tuple(getattr(state, f) for f in repl_fields + brk_fields)),
    ))
    kw = {f: jnp.asarray(_pad_rows(host[f], shape.R)) for f in repl_fields}
    D = shape.max_disks_per_broker
    for f in brk_fields:
        a = _pad_rows(host[f], shape.B)
        if f in ("disk_capacity", "disk_alive") and a.shape[1] < D:
            wide = np.zeros((shape.B, D), a.dtype)
            wide[:, : a.shape[1]] = a
            a = wide
        kw[f] = jnp.asarray(a)
    return dataclasses.replace(state, shape=shape, **kw)


def prewarm_state(shape: ClusterShape, *, max_rf: int = 1) -> ClusterState:
    """A minimal VALID ClusterState of `shape` for boot-time engine
    prewarm (analyzer/prewarm.py manifest replay).

    Engine programs specialize on shapes only — cluster data rides in as
    runtime arguments — so a placeholder is enough to trace+compile the
    exact programs the live model of the same bucket will run.  The one
    data-dependent aval axis is the partition replica table's width
    (max observed replication factor), so `max_rf` replicas of one
    partition are materialized on distinct brokers; everything else is
    zeros/defaults, front-packed so sampling-bound derivation matches a
    real monitor build.
    """
    import jax.numpy as jnp

    R, B, D = shape.R, shape.B, shape.max_disks_per_broker
    max_rf = max(1, min(int(max_rf), R, B))
    n = max_rf  # valid replicas: one partition, rf = max_rf
    r_broker = np.zeros(R, np.int32)
    r_broker[:n] = np.arange(n, dtype=np.int32)
    r_pos = np.zeros(R, np.int32)
    r_pos[:n] = np.arange(n, dtype=np.int32)
    r_leader = np.zeros(R, bool)
    r_leader[0] = True
    r_valid = np.zeros(R, bool)
    r_valid[:n] = True
    zeros_load = np.zeros((R, NUM_RESOURCES), np.float32)
    broker_valid = np.ones(B, bool)
    return ClusterState(
        replica_broker=jnp.asarray(r_broker),
        replica_partition=jnp.asarray(np.zeros(R, np.int32)),
        replica_topic=jnp.asarray(np.zeros(R, np.int32)),
        replica_pos=jnp.asarray(r_pos),
        replica_is_leader=jnp.asarray(r_leader),
        replica_valid=jnp.asarray(r_valid),
        replica_orig_broker=jnp.asarray(r_broker.copy()),
        replica_offline=jnp.asarray(np.zeros(R, bool)),
        replica_disk=jnp.asarray(np.zeros(R, np.int32)),
        replica_load_leader=jnp.asarray(zeros_load),
        replica_load_follower=jnp.asarray(zeros_load.copy()),
        broker_capacity=jnp.asarray(np.ones((B, NUM_RESOURCES), np.float32)),
        broker_rack=jnp.asarray(np.zeros(B, np.int32)),
        broker_host=jnp.asarray(
            np.arange(B, dtype=np.int32) % max(1, shape.num_hosts)
        ),
        broker_alive=jnp.asarray(np.ones(B, bool)),
        broker_new=jnp.asarray(np.zeros(B, bool)),
        broker_valid=jnp.asarray(broker_valid),
        disk_capacity=jnp.asarray(np.ones((B, D), np.float32)),
        disk_alive=jnp.asarray(np.ones((B, D), bool)),
        shape=shape,
    )


class ClusterModelBuilder:
    def __init__(
        self,
        *,
        replica_capacity: int | None = None,
        follower_cpu_fraction: float = 0.3,
        bucket_policy: ShapeBucketPolicy | None = None,
    ):
        self._brokers: list[BrokerSpec] = []
        self._partitions: list[PartitionSpec] = []
        self._replica_capacity = replica_capacity
        self._follower_cpu_fraction = follower_cpu_fraction
        self._bucket_policy = bucket_policy

    def add_broker(self, spec: BrokerSpec) -> "ClusterModelBuilder":
        self._brokers.append(spec)
        return self

    def add_partition(self, spec: PartitionSpec) -> "ClusterModelBuilder":
        self._partitions.append(spec)
        return self

    def build(self) -> ClusterState:
        ba = _broker_arrays(self._brokers)
        brokers = sorted(self._brokers, key=lambda b: b.broker_id)
        B = len(brokers)
        racks, hosts, D = ba.racks, ba.hosts, ba.D
        broker_alive = ba.alive
        disk_alive = ba.disk_alive
        topics = sorted({p.topic for p in self._partitions})
        topic_idx = {t: i for i, t in enumerate(topics)}

        parts = sorted(self._partitions, key=lambda p: (p.topic, p.partition))
        P = len(parts)
        n_replicas = sum(len(p.replica_brokers) for p in parts)
        R = self._replica_capacity or n_replicas
        if R < n_replicas:
            raise ValueError(f"replica_capacity {R} < actual replicas {n_replicas}")

        r_broker = np.zeros(R, np.int32)
        r_part = np.zeros(R, np.int32)
        r_topic = np.zeros(R, np.int32)
        r_pos = np.zeros(R, np.int32)
        r_leader = np.zeros(R, bool)
        r_valid = np.zeros(R, bool)
        r_offline = np.zeros(R, bool)
        r_disk = np.zeros(R, np.int32)
        r_ll = np.zeros((R, NUM_RESOURCES), np.float32)
        r_fl = np.zeros((R, NUM_RESOURCES), np.float32)

        k = 0
        for pid, p in enumerate(parts):
            ll = np.asarray(p.leader_load, np.float32)
            fl = (
                np.asarray(p.follower_load, np.float32)
                if p.follower_load is not None
                else default_follower_load(ll, self._follower_cpu_fraction)
            )
            for pos, bid in enumerate(p.replica_brokers):
                r_broker[k] = bid
                r_part[k] = pid
                r_topic[k] = topic_idx[p.topic]
                r_pos[k] = pos
                r_leader[k] = pos == p.leader_pos
                r_valid[k] = True
                disk = (p.replica_disks or [0] * len(p.replica_brokers))[pos]
                r_disk[k] = disk
                r_offline[k] = (not brokers[bid].alive) or (not disk_alive[bid, disk])
                r_ll[k] = ll
                r_fl[k] = fl
                k += 1

        self.catalog = ClusterCatalog(
            topics=tuple(topics),
            partitions=tuple((p.topic, p.partition) for p in parts),
            racks=tuple(racks),
            hosts=tuple(hosts),
        )
        shape = ClusterShape(
            num_replicas=R,
            num_brokers=B,
            num_partitions=P,
            num_topics=max(len(topics), 1),
            num_racks=max(len(racks), 1),
            num_hosts=max(len(hosts), 1),
            max_disks_per_broker=D,
        )
        if self._bucket_policy is not None:
            shape = self._bucket_policy.bucket_shape(shape)
        return _assemble_state(
            ba, shape,
            r_broker, r_part, r_topic, r_pos, r_leader, r_valid, r_offline,
            r_disk, r_ll, r_fl,
        )
