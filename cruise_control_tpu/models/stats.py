"""Cluster-level statistics (reference: model/ClusterModelStats.java:26).

AVG / MAX / MIN / ST_DEV per resource over alive brokers (reference
common/Statistic.java), replica- and leader-count dispersion, and potential
NW-out — the numbers goals compare before/after optimization
(reference analyzer/goals/AbstractGoal.java:92-101 regression check).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cruise_control_tpu.models.aggregates import BrokerAggregates, compute_aggregates
from cruise_control_tpu.models.state import ClusterState


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["avg", "max", "min", "std", "replica_count_std", "leader_count_std", "potential_nw_out_std"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class ClusterStats:
    avg: jax.Array  # f32[4] mean utilization over alive brokers
    max: jax.Array  # f32[4]
    min: jax.Array  # f32[4]
    std: jax.Array  # f32[4]
    replica_count_std: jax.Array  # f32 scalar
    leader_count_std: jax.Array  # f32 scalar
    potential_nw_out_std: jax.Array  # f32 scalar


def _masked_stats(x: jax.Array, mask: jax.Array):
    """Column stats of x[B, K] over rows where mask[B] (at least 1 assumed)."""
    n = jnp.maximum(mask.sum(), 1)
    m = mask[:, None] if x.ndim == 2 else mask
    xm = jnp.where(m, x, 0.0)
    mean = xm.sum(axis=0) / n
    var = (jnp.where(m, (x - mean) ** 2, 0.0)).sum(axis=0) / n
    big = jnp.asarray(jnp.inf, x.dtype)
    mx = jnp.where(m, x, -big).max(axis=0)
    mn = jnp.where(m, x, big).min(axis=0)
    return mean, mx, mn, jnp.sqrt(var)


def compute_stats(state: ClusterState, agg: BrokerAggregates | None = None) -> ClusterStats:
    if agg is None:
        agg = compute_aggregates(state)
    mask = state.broker_valid & state.broker_alive
    avg, mx, mn, std = _masked_stats(agg.broker_load, mask)
    _, _, _, rc_std = _masked_stats(agg.broker_replica_count.astype(jnp.float32), mask)
    _, _, _, lc_std = _masked_stats(agg.broker_leader_count.astype(jnp.float32), mask)
    _, _, _, pn_std = _masked_stats(agg.broker_potential_nw_out, mask)
    return ClusterStats(
        avg=avg, max=mx, min=mn, std=std,
        replica_count_std=rc_std, leader_count_std=lc_std, potential_nw_out_std=pn_std,
    )
