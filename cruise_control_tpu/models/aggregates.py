"""Broker-level aggregate views of a ClusterState.

Every O(replicas) TreeSet walk the reference performs inside goal hot loops
(reference: model/Broker.java trackedSortedReplicas, model/SortedReplicas.java:47)
becomes a single `segment_sum` here.  Aggregates are computed once per
optimizer step and updated incrementally by move deltas, so the per-candidate
cost is O(1) gathers rather than O(R).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.collectives import gscatter_rows, gsegment_sum
from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.models.state import ClusterState


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "broker_load",
        "broker_replica_count",
        "broker_leader_count",
        "broker_potential_nw_out",
        "broker_leader_bytes_in",
        "broker_topic_count",
        "part_rack_count",
        "disk_load",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class BrokerAggregates:
    """Per-broker reductions that every goal scores against.

    part_rack_count is the dense [P, num_racks] replica count used by the
    rack-awareness goal (reference analyzer/goals/RackAwareGoal.java:43): a
    partition is rack-aware iff no entry exceeds 1.
    """

    broker_load: jax.Array  # f32[B, NUM_RESOURCES]
    broker_replica_count: jax.Array  # i32[B]
    broker_leader_count: jax.Array  # i32[B]
    broker_potential_nw_out: jax.Array  # f32[B]
    broker_leader_bytes_in: jax.Array  # f32[B] NW_IN served by leaders only
    broker_topic_count: jax.Array  # i32[T, B] replicas of topic t on broker b
    part_rack_count: jax.Array  # i32[P, num_racks]
    disk_load: jax.Array  # f32[B, D] disk-resource bytes per logdir


def compute_aggregates(state: ClusterState) -> BrokerAggregates:
    # Replica rows may be a MODEL_AXIS shard-local slice (ids stay
    # global): gsegment_sum finishes each broker-indexed reduction with
    # a psum, and part_rack_count — the one partition-indexed output —
    # reduce-scatters so the carry keeps only this shard's rows.  With
    # no model axis in scope (common/collectives.py) both helpers are
    # the identity composition and this function is byte-for-byte the
    # single-device one.
    s = state.shape
    B, P = s.B, s.P
    seg = state.broker_segment_ids()  # [R], padding -> B overflow bucket
    valid = state.replica_valid

    load = state.replica_load  # [R, 4], already masked by valid
    broker_load = gsegment_sum(load, seg, num_segments=B + 1)[:B]

    ones = valid.astype(jnp.int32)
    broker_replica_count = gsegment_sum(ones, seg, num_segments=B + 1)[:B]

    leaders = (state.replica_is_leader & valid).astype(jnp.int32)
    broker_leader_count = gsegment_sum(leaders, seg, num_segments=B + 1)[:B]

    pot = jnp.where(valid, state.replica_load_leader[:, Resource.NW_OUT], 0.0)
    broker_potential_nw_out = gsegment_sum(pot, seg, num_segments=B + 1)[:B]

    lead_in = jnp.where(
        state.replica_is_leader & valid, state.replica_load_leader[:, Resource.NW_IN], 0.0
    )
    broker_leader_bytes_in = gsegment_sum(lead_in, seg, num_segments=B + 1)[:B]

    topic_seg = jnp.where(valid, state.replica_topic * B + state.replica_broker, s.num_topics * B)
    broker_topic_count = gsegment_sum(
        ones, topic_seg, num_segments=s.num_topics * B + 1
    )[: s.num_topics * B].reshape(s.num_topics, B)

    rack = state.broker_rack[state.replica_broker]  # [R]
    pr_seg = jnp.where(valid, state.replica_partition * s.num_racks + rack, P * s.num_racks)
    part_rack_count = gscatter_rows(
        jax.ops.segment_sum(
            ones, pr_seg, num_segments=P * s.num_racks + 1
        )[: P * s.num_racks].reshape(P, s.num_racks)
    )

    D = s.max_disks_per_broker
    disk_seg = jnp.where(valid, state.replica_broker * D + state.replica_disk, B * D)
    disk_load = gsegment_sum(
        jnp.where(valid, load[:, Resource.DISK], 0.0), disk_seg, num_segments=B * D + 1
    )[: B * D].reshape(B, D)

    return BrokerAggregates(
        broker_load=broker_load,
        broker_replica_count=broker_replica_count,
        broker_leader_count=broker_leader_count,
        broker_potential_nw_out=broker_potential_nw_out,
        broker_leader_bytes_in=broker_leader_bytes_in,
        broker_topic_count=broker_topic_count,
        part_rack_count=part_rack_count,
        disk_load=disk_load,
    )


def host_load(state: ClusterState, agg: BrokerAggregates) -> jax.Array:
    """f32[num_hosts, 4] — host-level utilization (CPU/NW are host resources,
    reference common/Resource.java:19-26, model/Host.java)."""
    return jax.ops.segment_sum(
        jnp.where(state.broker_valid[:, None], agg.broker_load, 0.0),
        jnp.where(state.broker_valid, state.broker_host, state.shape.num_hosts),
        num_segments=state.shape.num_hosts + 1,
    )[: state.shape.num_hosts]
