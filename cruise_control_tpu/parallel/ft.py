"""Mesh fault-tolerance controller: per-width breakers + degrade episodes.

The optimizer's mesh ladder (analyzer/optimizer.py `_optimize_mesh_ft`)
is stateless per call; this controller owns the state that must survive
across optimize calls so degradation behaves like the single-device
breaker, per mesh WIDTH:

  * one `CircuitBreaker` per mesh width (device count), lazily created —
    a width that just lost a chip opens ITS breaker, and subsequent
    optimize calls skip straight past it to the widest usable rung
    instead of re-failing a wedged width every request.  The supervisor's
    single-device breaker is never touched by a mesh failure (the
    `DeviceSupervisor.call(breaker=...)` substitution), so the plain
    engine and CPU-greedy rungs below the mesh stay healthy.
  * probing rides the breakers' own half-open machinery: once
    `probe_interval_s` elapses, the next optimize call's attempt at that
    width IS the probe (`acquire_width` returns the HALF_OPEN breaker);
    success closes it, failure re-arms the probe timer.
  * degrade EPISODES for the alert surface: the first width reduction
    opens an episode (`MESH_DEGRADED` fires exactly once, drained via
    `poll_event`), further reductions inside the same episode update
    `last_event` without re-firing, and a completed run at FULL width
    closes the episode so the next loss alerts again.

`CheckpointSlot` is the per-anneal carry-snapshot holder the optimizer
hands to `SegmentContext(snapshot_sink=...)` — latest-wins, thread-safe
(the persist runs on the segment runner's background snapshot thread).

Sensors (docs/sensors.md): `analyzer.mesh-ft.resumes`,
`analyzer.mesh-ft.checkpoint-seconds`, `analyzer.mesh-ft.active-width`
live here; `analyzer.mesh-ft.device-lost` is counted at the attribution
site (common/device_watchdog.DeviceSupervisor._attribute_mesh_failure).

Reference analog: none — the reference heals the Kafka cluster, not its
own compute substrate.
"""

from __future__ import annotations

import threading
import time

from cruise_control_tpu.common.device_watchdog import BreakerState, CircuitBreaker


class CheckpointSlot:
    """Latest-wins holder for one anneal's carry checkpoints."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ckpt = None

    def offer(self, ckpt) -> None:
        with self._lock:
            self._ckpt = ckpt

    def latest(self):
        with self._lock:
            return self._ckpt


class MeshFtController:
    """Cross-call mesh fault-tolerance state (config keys tpu.mesh.ft.*)."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        checkpoint_every_slices: int = 0,
        breaker_failure_threshold: int = 1,
        probe_interval_s: float = 30.0,
        sensors=None,
        clock=time.monotonic,
    ):
        self.enabled = bool(enabled)
        self.checkpoint_every_slices = int(checkpoint_every_slices)
        self.breaker_failure_threshold = int(breaker_failure_threshold)
        self.probe_interval_s = float(probe_interval_s)
        self.sensors = sensors
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: dict[int, CircuitBreaker] = {}
        #: degrade episodes so far (monotonic; the anomaly's episode id)
        self.episodes = 0
        self._episode_open = False
        #: width of the most recent completed/attempted mesh run
        self.active_width: int | None = None
        #: most recent degrade event (diagnostics; /state)
        self.last_event: dict | None = None
        self._pending_event: dict | None = None

    # -- per-width breakers ---------------------------------------------

    def breaker_for(self, width: int) -> CircuitBreaker:
        with self._lock:
            brk = self._breakers.get(int(width))
            if brk is None:
                brk = CircuitBreaker(
                    failure_threshold=self.breaker_failure_threshold,
                    probe_interval_s=self.probe_interval_s,
                    clock=self._clock,
                )
                self._breakers[int(width)] = brk
            return brk

    def acquire_width(self, width: int) -> CircuitBreaker | None:
        """The width's breaker when an attempt there is allowed right now:
        CLOSED, or OPEN with the probe due (the attempt serves as the
        half-open probe).  None = skip this rung."""
        brk = self.breaker_for(width)
        if brk.state is BreakerState.CLOSED:
            return brk
        if brk.begin_probe():
            return brk
        return None

    def note_width_result(self, width: int, *, ok: bool) -> None:
        """Complete the half-open probe lifecycle after an attempt whose
        breaker `acquire_width` handed out in HALF_OPEN (the supervisor's
        record_success/record_failure don't transition a half-open
        breaker)."""
        with self._lock:
            brk = self._breakers.get(int(width))
        if brk is None or brk.state is not BreakerState.HALF_OPEN:
            return
        if ok:
            brk.probe_succeeded()
        else:
            brk.probe_failed()

    # -- episodes / events ----------------------------------------------

    def note_degrade(
        self, *, lost, from_width: int, to_width: int, failure_class: str
    ) -> dict:
        """Record one width reduction; arms the MESH_DEGRADED event
        exactly when this opens a NEW episode."""
        with self._lock:
            new = not self._episode_open
            if new:
                self._episode_open = True
                self.episodes += 1
            self.active_width = int(to_width)
            event = dict(
                lost_devices=[int(d) for d in (lost or ())],
                from_width=int(from_width),
                to_width=int(to_width),
                failure_class=str(failure_class),
                episode=self.episodes,
                ms=int(time.time() * 1000),
            )
            self.last_event = event
            if new:
                self._pending_event = dict(event)
        if self.sensors is not None:
            self.sensors.gauge("analyzer.mesh-ft.active-width").set(int(to_width))
        return event

    def note_run_completed(
        self, *, width: int, full_width: int, resumed: bool = False
    ) -> None:
        """A mesh run finished at `width`; completing at FULL width closes
        the episode (re-arms the anomaly for the next loss)."""
        with self._lock:
            self.active_width = int(width)
            if int(width) == int(full_width) and self._episode_open:
                self._episode_open = False
        if self.sensors is not None:
            self.sensors.gauge("analyzer.mesh-ft.active-width").set(int(width))
            if resumed:
                self.sensors.counter("analyzer.mesh-ft.resumes").inc()

    def note_checkpoint_seconds(self, seconds: float) -> None:
        if seconds > 0 and self.sensors is not None:
            self.sensors.counter("analyzer.mesh-ft.checkpoint-seconds").inc(
                round(float(seconds), 6)
            )

    def poll_event(self) -> dict | None:
        """Drain the pending once-per-episode MESH_DEGRADED payload (the
        facade's detector round); None when already reported."""
        with self._lock:
            event, self._pending_event = self._pending_event, None
            return event

    @property
    def episode_open(self) -> bool:
        with self._lock:
            return self._episode_open

    def state_json(self) -> dict:
        with self._lock:
            out = {
                "enabled": self.enabled,
                "checkpointEverySlices": self.checkpoint_every_slices,
                "episodes": self.episodes,
                "episodeOpen": self._episode_open,
                "activeWidth": self.active_width,
                "breakers": {
                    str(w): b.snapshot() for w, b in sorted(self._breakers.items())
                },
            }
        if self.last_event is not None:
            out["lastEvent"] = dict(self.last_event)
        return out
