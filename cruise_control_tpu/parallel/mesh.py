"""Mesh-native engine layer: ONE sharded engine under sharded/grid/portfolio.

Every multi-device mode is a view of the same program over an explicit 2D
``Mesh((RESTART_AXIS, MODEL_AXIS))``:

  * MODEL_AXIS shards the CANDIDATE axis of the anneal.  Each step the
    full-K candidate index stream is drawn from the replicated RNG key
    (identical on every device), each shard evaluates objective deltas for
    only its contiguous K/n slice (``Engine._slice_draws``), and ONE tiled
    ``all_gather`` reassembles the candidate COLUMNS — delta, feasibility,
    src/dst broker, partition ids, apply payload — into full-K order for
    the global conflict resolution that then runs identically everywhere.
  * RESTART_AXIS runs independent annealing chains (different keys) racing
    to the best objective; the winner is selected on the host from the
    per-chain objectives that ride the run's single blocking sync.

  sharded  = Mesh(1, n)   grid:RxM = Mesh(R, M)   portfolio = Mesh(n, 1)

Why gather-candidates-only is safe: the model and the EngineCarry are
REPLICATED over MODEL_AXIS, and after the gather every device applies the
same surviving move set to the same carry — so placements and aggregates
stay byte-identical replicas with no psum'd refresh, no carry exchange,
and no cross-shard scatter.  Communication per step is O(K) candidate
columns — independent of the replica count — and it is the ONLY
collective in the program.

Byte parity by construction: the draws never depend on the mesh size
(full-K streams are drawn before slicing), per-candidate delta math is
row-local, and the gather reassembles the exact full-K order (slices are
edge-padded to n*ceil(K/n) and trimmed after the gather).  A 1-device and
an 8-device run of the same seeded anneal therefore produce identical
objectives, placements, and proposals — the property the virtual-mesh
dryrun and ``bench.py --mesh-smoke`` pin.

The whole multi-round schedule (temperature decay, aggregate refresh,
sampling-plan rebuild, early stop, extra polish rounds) reuses the plain
engine's fused scan-of-scans body (``Engine._fused_rounds_body``) with
the per-shard step swapped in, and the carry is donated — per restart
chain, HBM holds ONE placement copy.  At n=1 the traced program IS the
plain fused program (the slice is the identity and no collective is
emitted), which is what keeps the sharded n=1 overhead under 10%.

MODEL_AXIS additionally has a genuinely SHARDED-MODEL mode
(`model_shard_min_partitions` > 0 and the real partition count at or
above it): the replica/partition-indexed leaves of the statics and the
carry are partitioned over MODEL_AXIS in contiguous row blocks
(``models/sharding.py`` partition-rule tables drive both `device_put`
placement and the shard_map in/out specs), candidate row gathers resolve
by ownership psums, and the goal chain's segment sums run shard-local
with one psum (``parallel/model_shard._ModelShardEngine``).  Per-chip
model memory and per-step O(R)/O(P) FLOPs drop ~1/n — the mode that
carries 25k brokers / 2M partitions on an 8-chip mesh.  Unlike the
replaced rounds-1-5 ``parallel/sharded.py`` design (per-shard RNG
streams, no 1-vs-N parity, ~22% slower at n=1 — VERDICT r5 item 4), the
sharded-model mode keeps every RNG draw replicated, so placements stay
byte-identical to the replicated mesh whenever the psum'd objective
partials are exact (integer-quantized loads; float loads track to ulp).
Below the threshold the replicated candidate-sharding mode remains the
default — at small scale the model is tens of MB and candidate
throughput, not HBM, is the axis that pays.

Reference analog: none — the reference optimizer is a single-threaded
Java loop (analyzer/goals/AbstractGoal.java:66-107).
"""

from __future__ import annotations

import dataclasses
import logging
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from functools import partial

from cruise_control_tpu.analyzer.engine import (
    CarryCheckpoint,
    Engine,
    OptimizerConfig,
    SEGMENT_MAX_ROUNDS,
    SegmentContext,
    _WarmedFn,
    current_segment_context,
    snapshot_host_tree,
    start_warm_pool,
)
from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.common.blackbox import RECORDER as _BLACKBOX
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.common.dispatch import count_dispatch
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.sharding import (
    carry_partition_rules,
    match_partition_rules,
    shard_multiple_shape,
    statics_partition_rules,
)
from cruise_control_tpu.models.state import ClusterState, ShapeBucketPolicy
from cruise_control_tpu.parallel.model_shard import _ModelShardEngine

RESTART_AXIS = "restart"
MODEL_AXIS = "model"

log = logging.getLogger(__name__)


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """The ONE dual-import shard_map shim every mesh caller uses.

    jax >= 0.4.35 exposes shard_map at top level with `check_vma`; older
    releases keep it in jax.experimental with `check_rep`.  Consolidated
    here (it used to be copy-pasted per parallel module) so a jax upgrade
    is one edit."""
    try:
        from jax import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)
    except (ImportError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_rep=False)


def model_mesh(devices=None) -> Mesh:
    """1D candidate-sharding mesh (the "sharded" parallel mode)."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (MODEL_AXIS,))


def default_mesh(devices=None) -> Mesh:
    """1D restart-portfolio mesh (one chain per device)."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (RESTART_AXIS,))


def grid_mesh(n_restarts: int, n_shards: int, devices=None) -> Mesh:
    """2D (restart, model) mesh: R chains, each candidate-sharded M ways."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size < n_restarts * n_shards:
        raise ValueError(
            f"{devices.size} devices < {n_restarts}x{n_shards} grid"
        )
    grid = devices[: n_restarts * n_shards].reshape(n_restarts, n_shards)
    return Mesh(grid, (RESTART_AXIS, MODEL_AXIS))


def normalize_mesh(mesh: Mesh) -> Mesh:
    """Any supported mesh -> the canonical 2D (restart, model) mesh."""
    names = tuple(mesh.axis_names)
    if names == (RESTART_AXIS, MODEL_AXIS):
        return mesh
    devs = np.asarray(mesh.devices)
    if names == (MODEL_AXIS,):
        return Mesh(devs.reshape(1, -1), (RESTART_AXIS, MODEL_AXIS))
    if names == (RESTART_AXIS,):
        return Mesh(devs.reshape(-1, 1), (RESTART_AXIS, MODEL_AXIS))
    raise ValueError(
        f"mesh axes must be ({RESTART_AXIS!r},), ({MODEL_AXIS!r},) or "
        f"({RESTART_AXIS!r}, {MODEL_AXIS!r}); got {names}"
    )


def _gather_columns(raw, k_full: int):
    """Tiled all_gather of one candidate kind's column bundle back into
    full-K order.  Slices were edge-padded to n*ceil(K/n) rows, and the
    tiled gather concatenates the shards' contiguous slices in order, so
    the first k_full rows ARE the single-device stream."""
    def g(x):
        if x.shape[0] == 0:  # disabled kind: nothing to exchange
            return x
        return jax.lax.all_gather(x, MODEL_AXIS, tiled=True)[:k_full]

    return jax.tree.map(g, raw)


class _ShardStepEngine(Engine):
    """The inner Engine re-skinned for one mesh shard: `_step` evaluates
    only this shard's candidate slice and all_gathers the columns.

    Shares the parent engine's entire state (weights, config, statics
    layout) — only the step differs, so the fused rounds body, the early
    stop, and the sampling-plan rebuild are inherited verbatim and cannot
    diverge from the single-device semantics."""

    def __init__(self, engine: Engine, n_shards: int):  # noqa: D401
        # deliberately NOT calling Engine.__init__: this is a traced-code
        # twin, not a new engine — it shares every attribute (no re-jit)
        self.__dict__.update(engine.__dict__)
        self._mesh_n = n_shards

    def _step(self, sx, carry, temperature, plan=None):
        if self._mesh_n == 1:
            # identity slice, no collective: the traced program IS the
            # plain engine's step (the n=1 overhead guarantee)
            return Engine._step(self, sx, carry, temperature, plan)
        key, k_r, k_s, k_l, k_u = jax.random.split(carry.key, 5)
        g = self._globals(sx, carry)
        idx = jax.lax.axis_index(MODEL_AXIS)
        raw_r, raw_s, raw_l = self._propose_kinds(
            sx, carry, k_r, k_s, k_l, g, plan, slice_=(idx, self._mesh_n)
        )
        raw_r = _gather_columns(raw_r, self.K_r)
        raw_s = _gather_columns(raw_s, self.K_s)
        raw_l = _gather_columns(raw_l, self.K_l)
        prop = self._assemble_prop(sx, carry, raw_r, raw_s, raw_l)
        return self._accept_select_apply(sx, carry, prop, temperature, key, k_u)


class MeshEngine:
    """One engine for every multi-device mode (sharded / grid / portfolio).

    Construction pads the input to its shape bucket (when a policy is
    given) so compiled mesh programs survive topology churn exactly like
    the plain engine, places the statics explicitly as mesh-replicated
    arrays (`NamedSharding(mesh, P())` — arrays committed to one device
    by an earlier single-device run can never poison the mesh program,
    the r4 multichip failure mode), and builds the jitted shard_map
    programs.  `run()` executes the plain engine's fused multi-round
    schedule (`fused_rounds=False` has no mesh variant — the fused body
    is the only one); `run_schedule()` runs an explicit [rounds, steps]
    temperature schedule (the portfolio entry point).
    """

    def __init__(
        self,
        state: ClusterState,
        chain: GoalChain,
        mesh: Mesh | None = None,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig = OptimizerConfig(),
        bucket: ShapeBucketPolicy | None = None,
        model_shard_min_partitions: int = 0,
    ):
        self.mesh = normalize_mesh(mesh if mesh is not None else model_mesh())
        self._bucket = bucket if bucket is not None and bucket.enabled else None
        # sharded-model mode gate: opt-in threshold on the REAL partition
        # count (pre-padding — padding must not flip the mode between
        # generations of the same cluster) and a model axis to shard over
        self.model_sharded = (
            model_shard_min_partitions > 0
            and int(self.mesh.shape[MODEL_AXIS]) > 1
            and int(state.shape.P) >= int(model_shard_min_partitions)
        )
        self.global_state = state
        engine = Engine(
            self._padded(state), chain, constraint, options, config
        )
        self._finish_init(engine)

    @classmethod
    def from_engine(cls, engine: Engine, mesh: Mesh) -> "MeshEngine":
        """Wrap an EXISTING plain engine (portfolio_run's entry): reuses
        its statics/config; the caller's engine is never mutated."""
        self = object.__new__(cls)
        self.mesh = normalize_mesh(mesh)
        self._bucket = None
        self.model_sharded = False  # the wrapped engine's shape is as-is
        self.global_state = engine.state
        self._finish_init(engine)
        return self

    def _finish_init(self, engine: Engine) -> None:
        self.n_restarts = int(self.mesh.shape[RESTART_AXIS])
        self.n = int(self.mesh.shape[MODEL_AXIS])
        self.engine = engine
        if not engine.config.fused_rounds:
            # there is no mesh variant of the legacy per-round loop; the
            # fused schedule runs regardless, so say so instead of letting
            # a fused-vs-legacy comparison silently compare fused vs fused
            log.warning(
                "OptimizerConfig.fused_rounds=False has no mesh variant; "
                "the mesh engine always runs the fused schedule"
            )
        self._twin = self._make_twin(engine)
        #: diagnostics of the most recent COMPLETED run (None before/during)
        self.last_info: dict | None = None
        self._warm_futures: dict | None = None
        self._coll_bytes: int | None = None
        #: per-slice-length jitted segmented programs + the lazy
        #: segmented prelude/objective programs (mesh fault tolerance)
        self._seg_mesh_fns: dict = {}
        self._jit_seg_init_mesh = None
        self._jit_obj = None
        self._build_specs()
        self._place_statics()
        self._build_jits()

    def _blackbox_fields(self) -> dict:
        """Fields the `device_op` seam merges into this engine's
        "device-op" Begin records: a killed mesh dispatch's spool verdict
        names the mesh width in flight, not just the op."""
        return {
            "mesh_shape": [self.n_restarts, self.n],
            "n_devices": self.n_restarts * self.n,
        }

    def _make_twin(self, engine: Engine):
        if self.model_sharded:
            return _ModelShardEngine(engine, self.n)
        return _ShardStepEngine(engine, self.n)

    def _build_specs(self) -> None:
        """shard_map in/out spec trees for the statics and the (blocked)
        carry.  Replicated modes use the pytree-prefix specs (P() statics,
        P(RESTART_AXIS) carry) — the pre-sharding programs verbatim; the
        sharded-model mode expands them per-leaf from the models/sharding
        rule tables (the leading restart block axis does not change the
        carry's pytree structure, so the rules match unchanged)."""
        if not self.model_sharded:
            self._sx_specs = P()
            self._carry_specs = P(RESTART_AXIS)
            return
        self._sx_specs = match_partition_rules(
            statics_partition_rules(MODEL_AXIS), self.engine.statics
        )
        carry_av = jax.eval_shape(
            self.engine._init_impl,
            self.engine.statics_avals(),
            jax.ShapeDtypeStruct((2,), jnp.uint32),
        )
        self._carry_specs = match_partition_rules(
            carry_partition_rules(RESTART_AXIS, MODEL_AXIS), carry_av
        )

    # ------------------------------------------------------------------
    # data binding
    # ------------------------------------------------------------------

    def _padded(self, state: ClusterState) -> ClusterState:
        shape = state.shape
        if self._bucket is not None:
            shape = self._bucket.bucket_shape(shape)
        if self.model_sharded:
            # equal contiguous row blocks per shard (on TOP of the bucket
            # shape, so bucketed rebinds stay churn-stable too)
            shape = shard_multiple_shape(shape, self.n_model)
        if shape == state.shape:
            return state
        from cruise_control_tpu.models.builder import pad_state

        return pad_state(state, shape)

    @property
    def n_model(self) -> int:
        return int(self.mesh.shape[MODEL_AXIS])

    def _place_statics(self) -> None:
        """Mesh-replicated copies of the engine statics.  Explicit layout:
        relying on jit's input resharding breaks when an earlier
        single-device program COMMITTED the arrays to one device (the r4
        `portfolio.py:99` devices-mismatch crash); device_put with the
        mesh sharding is correct for committed and uncommitted inputs
        alike.  In sharded-model mode the placement follows the per-leaf
        partition-rule specs instead of blanket replication."""
        if self.model_sharded:
            shardings = jax.tree.map(
                lambda spec: NamedSharding(self.mesh, spec),
                self._sx_specs,
                is_leaf=lambda x: isinstance(x, P),
            )
            self.statics = jax.device_put(self.engine.statics, shardings)
        else:
            self.statics = jax.device_put(
                self.engine.statics, NamedSharding(self.mesh, P())
            )

    def rebind(
        self, state: ClusterState, options: OptimizationOptions = DEFAULT_OPTIONS
    ) -> "MeshEngine":
        """Swap in a new model generation without recompiling.  With a
        bucket policy the padded shape is churn-stable, so generations
        inside a bucket always rebind; a bucket overflow raises ValueError
        (the optimizer's signal to build a fresh engine)."""
        self.engine.rebind(self._padded(state), options)
        # the twin snapshot shares the engine's attributes by reference;
        # rebuild it so it can never pin a previous generation's statics
        # (the traced programs read statics from their argument, so this
        # is about buffer lifetime, not numerics)
        self._twin = self._make_twin(self.engine)
        self.global_state = state
        self._place_statics()
        return self

    def release(self) -> None:
        """Drop device buffers on engine-cache eviction.  The mesh
        statics copy's engine-derived arrays are deleted explicitly; the
        `state` leaves are only de-referenced (on a 1-device mesh
        device_put may alias the caller's buffers).  Unusable after."""
        sx = self.statics
        if sx is not None:
            for f in dataclasses.fields(type(sx)):
                if f.name == "state":
                    continue
                for leaf in jax.tree.leaves(getattr(sx, f.name)):
                    try:
                        leaf.delete()
                    except Exception:  # noqa: BLE001 — already-deleted/np
                        pass
        self.statics = None
        self.engine.release()
        self._twin = None  # drop the snapshot's statics reference too
        self.global_state = None
        self._warm_futures = None
        self._seg_mesh_fns = {}
        self._jit_seg_init_mesh = None
        self._jit_obj = None

    # ------------------------------------------------------------------
    # jitted mesh programs
    # ------------------------------------------------------------------

    def _build_jits(self) -> None:
        spec_r = P(RESTART_AXIS)
        self._jit_init = jax.jit(
            shard_map_compat(
                self._init_fn, self.mesh,
                in_specs=(self._sx_specs, spec_r), out_specs=self._carry_specs,
            )
        )
        # the fused whole-anneal program; the carry is DONATED so each
        # restart chain holds one placement copy in HBM
        self._jit_run = jax.jit(
            shard_map_compat(
                self._run_fn, self.mesh,
                in_specs=(self._sx_specs, self._carry_specs),
                out_specs=(self._carry_specs, spec_r, spec_r),
            ),
            donate_argnums=(1,),
        )
        self._jit_run_verbose = None  # built lazily (adds per-round eval)
        self._jit_schedule = None  # built lazily (portfolio entry point)

    # ---- traced bodies (blocks carry a leading restart axis of 1) ----

    def _init_fn(self, sx, keys_blk):
        carry = self._twin._init_impl(sx, keys_blk[0])
        return jax.tree.map(lambda x: x[None], carry)

    def _run_fn(self, sx, carry_blk):
        return self._run_body(sx, carry_blk, verbose=False)

    def _run_verbose_fn(self, sx, carry_blk):
        return self._run_body(sx, carry_blk, verbose=True)

    def _run_body(self, sx, carry_blk, *, verbose: bool):
        """One restart chain's fused anneal + its final SA objective (the
        host's winner-selection key, riding the same sync as the stats)."""
        eng = self._twin
        carry = jax.tree.map(lambda x: x[0], carry_blk)
        carry, ys = eng._fused_rounds_body(sx, carry, verbose=verbose)
        obj = eng.carry_objective(sx, carry)
        stack = lambda t: jax.tree.map(lambda x: x[None], t)  # noqa: E731
        return stack(carry), stack(ys), obj[None]

    def _schedule_fn(self, sx, carry_blk, temps2d):
        """Explicit-schedule chain (portfolio semantics): scan over temps
        rows with the between-rounds program after every round."""
        eng = self._twin
        carry = jax.tree.map(lambda x: x[0], carry_blk)
        plan = eng._plan_impl(sx, carry)

        def round_body(cp, t_row):
            c, p = cp
            c, stats = eng._scan_impl(sx, c, t_row, p)
            c, p, _cheap = eng._round_prep_impl(sx, c)
            return (c, p), stats["accepted"].sum()

        (carry, _), acc = jax.lax.scan(round_body, (carry, plan), temps2d)
        obj = eng.carry_objective(sx, carry)
        stack = lambda t: jax.tree.map(lambda x: x[None], t)  # noqa: E731
        return stack(carry), obj[None], acc[None]

    # ------------------------------------------------------------------
    # warm start (shared pool with the plain engine)
    # ------------------------------------------------------------------

    def precompile_async(self, *, priority: int = 0) -> None:
        """Trace+lower+compile the mesh programs on the SAME background
        warm pool the plain engine uses (engine.start_warm_pool) so the
        sharded variants' tracing overlaps the caller's serial prelude
        exactly like the single-device warm start.  No AOT artifacts
        here: shard_map'd programs bake mesh/sharding state that the
        round-4 export cache got wrong (VERDICT r4) — the mesh path warms
        by overlap only, at the given pool `priority`."""
        if self._warm_futures is not None:
            return
        sx_av = self.engine.statics_avals()
        key_av = jax.ShapeDtypeStruct((self.n_restarts, 2), jnp.uint32)
        base = jax.eval_shape(
            self.engine._init_impl, sx_av, jax.ShapeDtypeStruct((2,), jnp.uint32)
        )
        carry_av = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct((self.n_restarts,) + a.shape, a.dtype),
            base,
        )
        self._warm_futures = start_warm_pool([
            ("_jit_run", self._jit_run, (sx_av, carry_av)),
            ("_jit_init", self._jit_init, (sx_av, key_av)),
        ], priority=priority)

    def _fn(self, name: str):
        futs = self._warm_futures
        if futs is not None and name in futs:
            fut = futs.pop(name)
            try:
                setattr(self, name, _WarmedFn(fut.result(), getattr(self, name)))
            except Exception as e:  # noqa: BLE001 — fall back to lazy jit
                log.warning("mesh precompile of %s failed: %r", name, e)
        return getattr(self, name)

    # ------------------------------------------------------------------
    # collective accounting
    # ------------------------------------------------------------------

    @property
    def collective_bytes_per_step(self) -> int:
        """Bytes of candidate columns each device holds after the per-step
        gather (the run's ONLY collective): sum over exchanged leaves of
        n*ceil(K/n) padded rows.  0 on a 1-shard mesh (no collective is
        emitted).  Computed abstractly (eval_shape) — no device work.
        In sharded-model mode this is instead the twin's analytic
        ownership-psum byte count (there is no candidate gather)."""
        if self._coll_bytes is None:
            self._coll_bytes = (
                self._twin.psum_bytes_per_step()
                if self.model_sharded
                else self._compute_collective_bytes()
            )
        return self._coll_bytes

    @property
    def collective_bytes_per_round(self) -> int:
        if self.model_sharded:
            return self._twin.psum_bytes_per_round()
        return self.collective_bytes_per_step * self.engine.config.steps_per_round

    def _compute_collective_bytes(self) -> int:
        if self.n == 1:
            return 0
        eng = self.engine
        sx_av = eng.statics_avals()
        key_av = jax.ShapeDtypeStruct((2,), jnp.uint32)
        carry_av = jax.eval_shape(eng._init_impl, sx_av, key_av)
        plan_av = jax.eval_shape(eng._plan_impl, sx_av, carry_av)

        def probe(sx, carry, key, plan):
            g = eng._globals(sx, carry)
            k1, k2, k3 = jax.random.split(key, 3)
            return eng._propose_kinds(sx, carry, k1, k2, k3, g, plan)

        raw = jax.eval_shape(probe, sx_av, carry_av, key_av, plan_av)
        total = 0
        for leaf in jax.tree.leaves(raw):
            k = int(leaf.shape[0])
            rows = self.n * (-(-k // self.n))
            total += rows * int(np.prod(leaf.shape[1:], dtype=np.int64)) * leaf.dtype.itemsize
        return int(total)

    # ------------------------------------------------------------------
    # host-side drivers
    # ------------------------------------------------------------------

    @device_op("mesh.run")
    def run(self, *, verbose: bool = False, resume: CarryCheckpoint | None = None):
        """Execute (or RESUME) the fused schedule on the mesh.

        With an ambient SegmentContext (or an explicit `resume`
        checkpoint) the replicated modes run the schedule in wall-bounded
        slices — the preemption/fault-tolerance seam: carry snapshots
        ride the slice boundaries, and `resume` continues the remaining
        rounds from a CarryCheckpoint captured by ANY mesh width (the
        host trees carry no placement; restore is a device_put under this
        mesh's shardings).  The sharded-model mode has no segmented
        variant (its slice programs would need per-leaf plan specs);
        it always runs whole-schedule, and a mesh failure there restarts
        at the reduced width instead of resuming."""
        seg_ctx = current_segment_context()
        if not verbose and not self.model_sharded and (
            seg_ctx is not None or resume is not None
        ):
            if seg_ctx is None:
                # FT resume outside a scheduler grant: slice only for
                # checkpoint cadence, never for wall bounding
                seg_ctx = SegmentContext(float("inf"))
            return self._run_segmented(seg_ctx, resume=resume)
        if resume is not None:
            raise ValueError(
                "mesh resume requires the segmented path (replicated "
                "modes, non-verbose)"
            )
        return self._run(verbose=verbose)

    def _run(self, *, verbose: bool = False):
        """Execute the fused multi-round schedule on the mesh; returns
        (final_state, history) with the plain engine's history contract
        (winner chain's rounds; `accepted` summed over chains) plus a
        timing record carrying `mesh_shape` and `collective_bytes`."""
        cfg = self.engine.config
        self.last_info = None  # never report a previous run's diagnostics
        t_start = time.monotonic()
        # chain 0 of a 1-chain mesh uses the PLAIN engine's key so the
        # sharded run reproduces the single-device anneal byte-for-byte;
        # portfolios split per-chain keys exactly like portfolio_run
        keys = (
            jax.random.PRNGKey(cfg.seed)[None]
            if self.n_restarts == 1
            else jax.random.split(jax.random.PRNGKey(cfg.seed), self.n_restarts)
        )
        carry = self._fn("_jit_init")(self.statics, keys)
        if verbose:
            if self._jit_run_verbose is None:
                self._jit_run_verbose = jax.jit(
                    shard_map_compat(
                        self._run_verbose_fn, self.mesh,
                        in_specs=(self._sx_specs, self._carry_specs),
                        out_specs=(
                            self._carry_specs, P(RESTART_AXIS), P(RESTART_AXIS)
                        ),
                    ),
                    donate_argnums=(1,),
                )
            fused = self._jit_run_verbose
        else:
            fused = self._fn("_jit_run")
        carry, ys, objs = fused(self.statics, carry)
        t_disp = time.monotonic()
        # the run's ONE blocking sync: O(chains * rounds) scalars; the
        # final carries stay on device until the winner extraction below
        ys, objs = jax.device_get((ys, objs))
        t_sync = time.monotonic()
        objs = np.asarray(objs)
        winner = int(np.argmin(objs))
        win_carry = jax.tree.map(lambda x: x[winner], carry)
        state = self.final_state(win_carry)
        history = self._history(ys, winner, cfg, verbose)
        timing = dict(
            timing=True, fused=True, blocking_syncs=1,
            host_dispatch_s=round(t_disp - t_start, 6),
            device_s=round(t_sync - t_disp, 6),
            mesh_shape=[self.n_restarts, self.n],
            collective_bytes=self.collective_bytes_per_round,
        )
        if self.model_sharded:
            # only present when sharded — replicated-mode history records
            # (and everything downstream that hashes them) stay unchanged
            timing["model_sharded"] = True
            timing["model_psum_bytes"] = int(self._twin.psum_bytes_per_round())
        if cfg.diagnostics:
            # convergence summary with the SAME aggregation as the
            # per-round history records above: COUNT fields sum over all
            # chains (accepted == sum(kinds) holds, and the summary can be
            # cross-checked against the round records), while STATE
            # metrics (objective trajectory, final per-goal violations,
            # ran/early-stop) are the winner chain's — the trajectory the
            # served placement actually followed
            win_ys = {k: np.asarray(v)[winner] for k, v in ys.items()}
            for k in ("accepted", "acc_replica", "acc_swap", "acc_lead",
                      "prior_cands", "prior_acc"):
                win_ys[k] = np.asarray(ys[k]).sum(axis=0)
            timing["convergence"] = self.engine._convergence_summary(win_ys)
        history.append(timing)
        self.last_info = dict(
            objectives=objs, winner=winner,
            n_chains=self.n_restarts, n_shards=self.n,
        )
        return state, history

    # ------------------------------------------------------------------
    # segmented (preemptible / checkpointable) mesh execution
    # ------------------------------------------------------------------

    def _seg_init_fn(self, sx, keys_blk):
        """Per-shard segmented prelude: round-0 carry + scan state."""
        eng = self._twin
        carry = eng._init_impl(sx, keys_blk[0])
        seg = eng._seg_init_impl(sx, carry)
        stack = lambda t: jax.tree.map(lambda x: x[None], t)  # noqa: E731
        return stack(carry), stack(seg)

    def _seg_slice_fn(self, L, sx, carry_blk, seg_blk, base):
        """Rounds [base, base+L) of one restart chain — the plain
        engine's `_seg_slice_impl` under the mesh twin, so the sliced
        scan composes to exactly the unsegmented mesh program."""
        eng = self._twin
        carry = jax.tree.map(lambda x: x[0], carry_blk)
        seg = jax.tree.map(lambda x: x[0], seg_blk)
        carry, seg, ys = eng._seg_slice_impl(L, sx, carry, seg, base)
        stack = lambda t: jax.tree.map(lambda x: x[None], t)  # noqa: E731
        return stack(carry), stack(seg), stack(ys)

    def _obj_fn(self, sx, carry_blk):
        eng = self._twin
        carry = jax.tree.map(lambda x: x[0], carry_blk)
        return eng.carry_objective(sx, carry)[None]

    def _seg_mesh_fn(self, L: int):
        fn = self._seg_mesh_fns.get(L)
        if fn is None:
            spec_r = P(RESTART_AXIS)
            fn = jax.jit(
                shard_map_compat(
                    partial(self._seg_slice_fn, L), self.mesh,
                    in_specs=(self._sx_specs, self._carry_specs, spec_r, P()),
                    out_specs=(self._carry_specs, spec_r, spec_r),
                ),
                donate_argnums=(1, 2),
            )
            self._seg_mesh_fns[L] = fn
        return fn

    def checkpoint_capture(self, carry, seg, base: int, ys_parts) -> CarryCheckpoint:
        """Host-side CarryCheckpoint of a slice boundary (device idle):
        global numpy trees — no placement — so a narrower mesh can
        restore it with a plain device_put under ITS shardings."""
        count_dispatch("mesh.snapshot")
        return CarryCheckpoint(
            base=int(base),
            carry=snapshot_host_tree(carry),
            seg=snapshot_host_tree(seg),
            ys_parts=[dict(p) for p in ys_parts],
            n_chains=self.n_restarts,
            meta=dict(
                seed=int(self.engine.config.seed),
                mesh_shape=[self.n_restarts, self.n],
            ),
        )

    def _restore_checkpoint(self, ckpt: CarryCheckpoint):
        """device_put a CarryCheckpoint under THIS mesh's shardings.

        The device trees are re-materialized with an eager jnp.copy per
        leaf: device_put of a host tree can ZERO-COPY alias suitably
        aligned numpy buffers (observed on the CPU backend for a subset
        of leaves), and the slice programs donate the carry/seg — a
        donated alias lets XLA scribble its outputs straight into (or
        free) the checkpoint's own memory, silently corrupting it for
        any later resume from the same snapshot (a second degrade in
        one episode, or a retry at another width).  An eager copy op
        always allocates fresh XLA-owned output buffers, so what gets
        donated is never the checkpoint."""
        if int(ckpt.n_chains) != self.n_restarts:
            raise ValueError(
                f"checkpoint has {ckpt.n_chains} chains; this mesh runs "
                f"{self.n_restarts} — resume requires matching chains"
            )
        shard_r = NamedSharding(self.mesh, P(RESTART_AXIS))
        own = lambda t: jax.tree.map(  # noqa: E731
            jnp.copy, jax.device_put(t, shard_r)
        )
        carry = own(ckpt.carry)
        seg = own(ckpt.seg)
        return carry, seg, [dict(p) for p in ckpt.ys_parts], int(ckpt.base)

    def _run_segmented(
        self,
        seg_ctx: SegmentContext,
        *,
        resume: CarryCheckpoint | None = None,
    ):
        """The mesh fused schedule in wall-bounded slices (replicated
        modes): the plain engine's `_run_segmented` loop with every slice
        a whole shard_map program — a mesh slice is never a split
        collective.  Byte parity with the unsegmented mesh run holds by
        scan composition exactly like the single-device pin
        (tests/test_mesh_ft.py); slice boundaries are where the
        fault-tolerance layer captures carry snapshots and where a resume
        re-enters the remaining round schedule."""
        cfg = self.engine.config
        self.last_info = None
        t_start = time.monotonic()
        total = cfg.num_rounds + cfg.extra_round_budget
        budget = max(1e-3, float(seg_ctx.slice_budget_s))
        if resume is not None:
            carry, seg, ys_parts, base = self._restore_checkpoint(resume)
        else:
            keys = (
                jax.random.PRNGKey(cfg.seed)[None]
                if self.n_restarts == 1
                else jax.random.split(
                    jax.random.PRNGKey(cfg.seed), self.n_restarts
                )
            )
            if self._jit_seg_init_mesh is None:
                self._jit_seg_init_mesh = jax.jit(
                    shard_map_compat(
                        self._seg_init_fn, self.mesh,
                        in_specs=(self._sx_specs, P(RESTART_AXIS)),
                        out_specs=(self._carry_specs, P(RESTART_AXIS)),
                    )
                )
            count_dispatch("mesh.init")
            carry, seg = self._jit_seg_init_mesh(self.statics, keys)
            ys_parts = []
            base = 0
        device_s = 0.0
        round_wall = None
        L = 1
        slice_i = 0
        while base < total:
            first_use = L not in self._seg_mesh_fns
            t0s = time.monotonic()
            bb_seq = _BLACKBOX.begin(
                "engine-slice",
                slice=slice_i, base_round=int(base), rounds=int(L),
                total_rounds=int(total),
                mesh_shape=[self.n_restarts, self.n],
                n_devices=self.n_restarts * self.n,
            ) if _BLACKBOX.enabled else 0
            try:
                count_dispatch("mesh.slice")
                carry, seg, ys = self._seg_mesh_fn(L)(
                    self.statics, carry, seg, jnp.asarray(base, jnp.int32)
                )
                count_dispatch("mesh.sync")
                ys_host, done_host = jax.device_get((ys, seg[2]))
            except BaseException as e:  # noqa: BLE001 — recorded, re-raised
                _BLACKBOX.end(bb_seq, ok=False, error=repr(e))
                raise
            done = bool(np.all(done_host))
            _BLACKBOX.end(bb_seq, done=done)
            wall = time.monotonic() - t0s
            device_s += wall
            ys_parts.append(ys_host)
            base += L
            slice_i += 1
            per_round = wall / L
            if round_wall is None:
                round_wall = per_round
            elif not first_use:
                round_wall = 0.5 * round_wall + 0.5 * per_round
            if done or base >= total:
                break
            L = 1
            while L * 2 * round_wall <= budget and L * 2 <= SEGMENT_MAX_ROUNDS:
                L *= 2
            if seg_ctx.checkpoint is not None:
                seg_ctx.checkpoint()
            # FT carry snapshot: device idle (the sync above), carry/seg
            # not yet donated into the next slice — the copy races
            # nothing; one predicate when checkpointing is off
            seg_ctx.offer_snapshot(
                lambda c=carry, s=seg, b=base, p=ys_parts:
                    self.checkpoint_capture(c, s, b, p)
            )
        if self._jit_obj is None:
            self._jit_obj = jax.jit(
                shard_map_compat(
                    self._obj_fn, self.mesh,
                    in_specs=(self._sx_specs, self._carry_specs),
                    out_specs=P(RESTART_AXIS),
                )
            )
        count_dispatch("mesh.sync")
        objs = np.asarray(jax.device_get(self._jit_obj(self.statics, carry)))
        winner = int(np.argmin(objs))
        win_carry = jax.tree.map(lambda x: x[winner], carry)
        state = self.final_state(win_carry)
        ys = {
            k: np.concatenate([np.asarray(p[k]) for p in ys_parts], axis=1)
            for k in ys_parts[0]
        }
        history = self._history(ys, winner, cfg, verbose=False)
        timing = dict(
            timing=True, fused=True, segmented=True,
            segments=len(ys_parts), blocking_syncs=len(ys_parts) + 1,
            device_s=round(device_s, 6),
            host_dispatch_s=round(
                time.monotonic() - t_start - device_s, 6
            ),
            mesh_shape=[self.n_restarts, self.n],
            collective_bytes=self.collective_bytes_per_round,
        )
        if resume is not None:
            timing["resumed_from_round"] = int(resume.base)
        if seg_ctx.snapshots_taken or seg_ctx.snapshots_skipped:
            timing["snapshots"] = seg_ctx.snapshots_taken
            timing["snapshot_s"] = round(seg_ctx.snapshot_seconds, 6)
        if cfg.diagnostics:
            win_ys = {k: np.asarray(v)[winner] for k, v in ys.items()}
            for k in ("accepted", "acc_replica", "acc_swap", "acc_lead",
                      "prior_cands", "prior_acc"):
                win_ys[k] = np.asarray(ys[k]).sum(axis=0)
            timing["convergence"] = self.engine._convergence_summary(win_ys)
        history.append(timing)
        self.last_info = dict(
            objectives=objs, winner=winner,
            n_chains=self.n_restarts, n_shards=self.n,
        )
        return state, history

    def _history(self, ys, winner: int, cfg, verbose: bool) -> list[dict]:
        """Rebuild the plain engine's history shape from the winner
        chain's per-round flags (Engine._run_fused's exact loop, so a
        1-chain mesh run's history matches the plain engine's)."""
        ran = np.asarray(ys["ran"])[winner]
        stopped = np.asarray(ys["stopped"])[winner]
        temp = np.asarray(ys["temperature"])[winner]
        accepted = np.asarray(ys["accepted"])  # [chains, rounds]
        history: list[dict] = []
        for r in range(len(ran)):
            if stopped[r] and history:
                history[-1]["early_stop"] = True
            if not ran[r]:
                continue
            rec = dict(
                round=len(history),
                temperature=float(temp[r]),
                accepted=int(accepted[:, r].sum()),
            )
            if r >= cfg.num_rounds:
                rec["extra"] = True
            if cfg.diagnostics:
                # engine._fused_history record shape, one schema for
                # downstream consumers.  COUNTS (accepted_by_kind, prior)
                # sum over chains exactly like the pre-existing `accepted`
                # field, so accepted == sum(accepted_by_kind) holds on a
                # multi-chain mesh too; STATE metrics (objective, per-goal
                # violations) are the winner chain's — they describe the
                # placement actually served, and are not additive
                rec["objective"] = float(np.asarray(ys["objective"])[winner, r])
                rec["goal_violations"] = [
                    round(float(v), 8)
                    for v in np.asarray(ys["goal_viol"])[winner, r]
                ]
                rec["accepted_by_kind"] = {
                    "replica": int(np.asarray(ys["acc_replica"])[:, r].sum()),
                    "swap": int(np.asarray(ys["acc_swap"])[:, r].sum()),
                    "leadership": int(np.asarray(ys["acc_lead"])[:, r].sum()),
                }
                rec["prior"] = {
                    "candidates": int(np.asarray(ys["prior_cands"])[:, r].sum()),
                    "accepted": int(np.asarray(ys["prior_acc"])[:, r].sum()),
                }
            elif verbose:
                rec["objective"] = float(np.asarray(ys["objective"])[winner, r])
            history.append(rec)
        return history

    def run_schedule(self, temps, *, seed: int = 0):
        """Run one chain per restart group through an EXPLICIT temperature
        schedule (f32[S] or f32[rounds, S]); returns (best final state,
        {"objectives": f32[chains], "n_chains", "n_shards", "winner"}).
        The portfolio entry point — all rounds device-resident, one
        winner-selection sync."""
        temps = jnp.asarray(temps, jnp.float32)
        if temps.ndim == 1:
            temps = temps[None]
        if self._jit_schedule is None:
            self._jit_schedule = jax.jit(
                shard_map_compat(
                    self._schedule_fn, self.mesh,
                    in_specs=(self._sx_specs, self._carry_specs, P()),
                    out_specs=(
                        self._carry_specs, P(RESTART_AXIS), P(RESTART_AXIS)
                    ),
                ),
                donate_argnums=(1,),
            )
        keys = jax.random.split(jax.random.PRNGKey(seed), self.n_restarts)
        carry = self._jit_init(self.statics, keys)
        carry, objs, acc = self._jit_schedule(self.statics, carry, temps)
        objs = np.asarray(jax.device_get(objs))
        winner = int(np.argmin(objs))
        state = self.final_state(jax.tree.map(lambda x: x[winner], carry))
        info = dict(
            objectives=objs, n_chains=self.n_restarts, n_shards=self.n,
            winner=winner, accepted=np.asarray(acc),
        )
        self.last_info = info
        return state, info

    def final_state(self, carry) -> ClusterState:
        """Winner carry -> ClusterState on the CALLER's original (unpadded)
        axes.  pad_state appends padding rows, so the original replicas are
        the leading slice of the padded placement."""
        rb, rl, rd = jax.device_get(
            (carry.replica_broker, carry.replica_is_leader, carry.replica_disk)
        )
        st = self.global_state
        R = st.shape.R
        rb, rl, rd = np.asarray(rb)[:R], np.asarray(rl)[:R], np.asarray(rd)[:R]
        alive = np.asarray(st.broker_alive)
        dalive = np.asarray(st.disk_alive)
        offline = ~(alive[rb] & dalive[rb, rd]) & np.asarray(st.replica_valid)
        return dataclasses.replace(
            st,
            replica_broker=jnp.asarray(rb),
            replica_is_leader=jnp.asarray(rl),
            replica_disk=jnp.asarray(rd),
            replica_offline=jnp.asarray(offline),
        )
