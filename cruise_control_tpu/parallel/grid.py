"""2D-mesh optimization: restart portfolio OVER candidate-sharded chains.

``GridEngine`` is the ``Mesh((restart=R, model=M))`` view of the shared
mesh engine layer (parallel/mesh.py): R independent annealing chains race
to the best objective, each with its candidate axis sharded M ways.  For a
v5e-16 slice this means e.g. ``grid_mesh(4, 4)``: 4 restarts x 4-way
candidate shards — chain diversity AND per-chain candidate throughput
scale together.  The collectives are scoped to the model axis, so chains
never interact until the host-side winner selection.

Deliberately thin: the jit/shard_map plumbing that used to live here is
parallel/mesh.py, shared verbatim with sharded.py and portfolio.py.
"""

from __future__ import annotations

from jax.sharding import Mesh

from cruise_control_tpu.analyzer.engine import OptimizerConfig
from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.parallel.mesh import (
    MODEL_AXIS,
    RESTART_AXIS,
    MeshEngine,
    grid_mesh,
)

__all__ = ["GridEngine", "grid_mesh", "MODEL_AXIS", "RESTART_AXIS"]


class GridEngine(MeshEngine):
    """MeshEngine constructed from an explicit 2D (restart, model) mesh.

    Kept as a named class (rather than MeshEngine directly) for the
    ``tpu.parallel.mode=grid:RxM`` wiring and its tests: a grid mode must
    be handed a genuine 2D mesh, not silently reshaped from whatever
    devices were lying around."""

    def __init__(
        self,
        state: ClusterState,
        chain: GoalChain,
        mesh: Mesh,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig = OptimizerConfig(),
        bucket=None,
        model_shard_min_partitions: int = 0,
    ):
        if tuple(mesh.axis_names) != (RESTART_AXIS, MODEL_AXIS):
            raise ValueError(
                f"grid mesh must have axes ({RESTART_AXIS!r}, {MODEL_AXIS!r})"
            )
        super().__init__(
            state, chain, mesh=mesh, constraint=constraint, options=options,
            config=config, bucket=bucket,
            model_shard_min_partitions=model_shard_min_partitions,
        )
