"""2D-mesh optimization: restart portfolio OVER model-sharded chains.

Composes the two parallel axes (SURVEY §2.6/§7 M6) the way a training
stack composes data and model parallelism:

  mesh ("restart", "model"): each restart group runs ONE independent
  annealing chain whose cluster model is sharded across the "model" axis
  (parallel/sharded.py semantics — all_gather'd candidates, psum'd
  refresh, collectives scoped to "model" so chains never interact); the
  best chain is selected at the end by comparing per-chain objectives.

For a v5e-16 slice this means e.g. Mesh(4, 4): 4 restarts × 4-way model
shards — candidate throughput AND HBM capacity scale together.  The
statics (cluster data) are sharded over "model" and replicated over
"restart": each model shard is stored once per restart group, never per
device pair.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from cruise_control_tpu.analyzer.engine import OptimizerConfig
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.parallel.portfolio import RESTART_AXIS
from cruise_control_tpu.parallel.sharded import (
    MODEL_AXIS,
    ShardedEngine,
    _restack,
    _shard_map,
    _unstack,
)


def grid_mesh(n_restarts: int, n_shards: int, devices=None) -> Mesh:
    devices = np.asarray(devices if devices is not None else jax.devices())
    if devices.size < n_restarts * n_shards:
        raise ValueError(
            f"{devices.size} devices < {n_restarts}x{n_shards} grid"
        )
    grid = devices[: n_restarts * n_shards].reshape(n_restarts, n_shards)
    return Mesh(grid, (RESTART_AXIS, MODEL_AXIS))


class GridEngine(ShardedEngine):
    """ShardedEngine whose carry carries an extra leading restart axis.

    The traced per-shard bodies are inherited unchanged — their collectives
    name MODEL_AXIS explicitly, so under the 2D mesh each restart group is
    an isolated chain; only the block (un)stacking and the final winner
    selection differ.
    """

    def __init__(
        self,
        state: ClusterState,
        chain: GoalChain,
        mesh: Mesh,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig = OptimizerConfig(),
        bucket=None,
    ):
        if tuple(mesh.axis_names) != (RESTART_AXIS, MODEL_AXIS):
            raise ValueError(
                f"grid mesh must have axes ({RESTART_AXIS!r}, {MODEL_AXIS!r})"
            )
        self.n_restarts = int(mesh.shape[RESTART_AXIS])
        #: diagnostics of the most recent COMPLETED run (None before/during)
        self.last_info: dict | None = None
        super().__init__(
            state, chain, mesh=mesh, constraint=constraint, options=options,
            config=config, bucket=bucket,
        )

    # ---- spec/stacking overrides: carry leaves are [r, m, ...] ----

    def _build_jits(self):
        spec_sx = P(MODEL_AXIS)     # statics: sharded by model, replicated
        spec_c = P(RESTART_AXIS, MODEL_AXIS)  # per-chain, per-shard carry
        self._jit_init = jax.jit(
            _shard_map(self._init_fn, self.mesh,
                       in_specs=(spec_sx, spec_c), out_specs=spec_c)
        )
        self._jit_round = jax.jit(
            _shard_map(self._round_fn, self.mesh,
                       in_specs=(spec_sx, spec_c, P()),
                       out_specs=(spec_c, spec_c))
        )
        # fused multi-round program (inherited _run_fn body; the MODEL_AXIS
        # collectives keep each restart chain isolated under the 2D mesh)
        self._jit_run = jax.jit(
            _shard_map(self._run_fn, self.mesh,
                       in_specs=(spec_sx, spec_c, P()),
                       out_specs=(spec_c, spec_c)),
            donate_argnums=(1,),
        )
        self._jit_obj = jax.jit(
            _shard_map(self._obj_fn, self.mesh,
                       in_specs=(spec_sx, spec_c), out_specs=spec_c)
        )

    def _unstack_carry(self, blk):
        return jax.tree.map(lambda x: x[0, 0], blk)

    def _restack_carry(self, tree):
        return jax.tree.map(lambda x: x[None, None], tree)

    def _restack_stats(self, tree):
        return jax.tree.map(lambda x: x[None, None], tree)

    # ---- traced entry points (blocks: sx [1,...], carry [1,1,...]) ----

    def _init_fn(self, sx_blk, keys_blk):
        sx = _unstack(sx_blk)
        key = keys_blk[0, 0]
        carry = self._zero_carry(sx, key)
        return self._restack_carry(self._sharded_refresh(sx, carry))

    def _round_fn(self, sx_blk, carry_blk, temps):
        sx = _unstack(sx_blk)
        carry = self._unstack_carry(carry_blk)
        carry, stats = self._run_round(sx, carry, temps)
        return self._restack_carry(carry), self._restack_stats(stats)

    def _obj_fn(self, sx_blk, carry_blk):
        obj = self._sharded_objective(_unstack(sx_blk), self._unstack_carry(carry_blk))
        return obj[None, None]

    def objective(self, carry) -> float:
        """Best chain's objective (the inherited accessor assumes a 1D
        model-only mesh)."""
        return float(np.asarray(self._jit_obj(self.statics, carry))[:, 0].min())

    # ---- host-side driver ----

    @device_op("grid.run")
    def run(self, *, verbose: bool = False):
        self.last_info = None  # never report a previous run's diagnostics
        cfg = self.engine.config
        if not cfg.fused_rounds:
            return self._run_legacy(verbose=verbose)
        t_start = time.monotonic()
        keys = jax.random.split(
            jax.random.PRNGKey(cfg.seed), self.n_restarts * self.n
        ).reshape(self.n_restarts, self.n, 2)
        carry = self._jit_init(self.statics, keys)
        objs0 = np.asarray(self._jit_obj(self.statics, carry))  # sync 1
        t0_obj = float(objs0[0, 0]) * cfg.init_temperature_scale
        temps = self._temp_schedule(t0_obj)
        t_disp = time.monotonic()
        carry, ys = self._jit_run(self.statics, carry, jnp.asarray(temps))
        ys = jax.device_get(ys)  # sync 2: per-round, per-chain scalars
        t_sync = time.monotonic()
        accepted = np.asarray(ys["accepted"])  # [restarts, model, rounds]
        objectives = np.asarray(ys["objective"])
        history = []
        for rnd in range(cfg.num_rounds):
            rec = dict(
                round=rnd, temperature=float(temps[rnd, 0]),
                # per-chain counts: the stat is replicated across the model
                # axis (computed from the all-gathered candidate set), so
                # take shard column 0 of each chain
                accepted=int(accepted[:, 0, rnd].sum()),
            )
            if verbose:
                rec["objectives"] = objectives[:, 0, rnd].tolist()
            history.append(rec)
        history.append(dict(
            timing=True, fused=True, blocking_syncs=2,
            host_dispatch_s=round(t_disp - t_start, 6),
            device_s=round(t_sync - t_disp, 6),
        ))
        # winner: best chain by final objective (identical across the model
        # axis of a chain — take column 0; already fetched with the stats)
        objs = objectives[:, 0, -1]
        winner = int(np.argmin(objs))
        win_carry = jax.tree.map(lambda x: x[winner], carry)
        state = self.final_state(win_carry)
        #: per-run diagnostics beyond the uniform (state, history) contract
        self.last_info = {
            "objectives": objs, "winner": winner,
            "n_chains": self.n_restarts, "n_shards": self.n,
        }
        return state, history

    def _run_legacy(self, *, verbose: bool = False):
        """Legacy per-round loop (one dispatch + stats sync per round)."""
        cfg = self.engine.config
        t_start = time.monotonic()
        syncs = 0
        keys = jax.random.split(
            jax.random.PRNGKey(cfg.seed), self.n_restarts * self.n
        ).reshape(self.n_restarts, self.n, 2)
        carry = self._jit_init(self.statics, keys)
        objs0 = np.asarray(self._jit_obj(self.statics, carry))
        syncs += 1
        t0_obj = float(objs0[0, 0]) * cfg.init_temperature_scale
        history = []
        for rnd in range(cfg.num_rounds):
            t_round = (
                0.0 if rnd == cfg.num_rounds - 1
                else t0_obj * (cfg.temperature_decay**rnd)
            )
            temps = jnp.full((cfg.steps_per_round,), t_round, jnp.float32)
            carry, stats = self._jit_round(self.statics, carry, temps)
            rec = dict(
                round=rnd, temperature=t_round,
                accepted=int(np.asarray(stats["accepted"])[:, 0].sum()),
            )
            syncs += 1
            if verbose:
                rec["objectives"] = np.asarray(
                    self._jit_obj(self.statics, carry)
                )[:, 0].tolist()
                syncs += 1
            history.append(rec)
        objs = np.asarray(self._jit_obj(self.statics, carry))[:, 0]
        syncs += 1
        winner = int(np.argmin(objs))
        win_carry = jax.tree.map(lambda x: x[winner], carry)
        state = self.final_state(win_carry)
        history.append(dict(
            timing=True, fused=False, blocking_syncs=syncs,
            wall_s=round(time.monotonic() - t_start, 6),
        ))
        self.last_info = {
            "objectives": objs, "winner": winner,
            "n_chains": self.n_restarts, "n_shards": self.n,
        }
        return state, history
