"""Model-axis sharding twin: the flattened cluster model partitioned over
MODEL_AXIS.

`_ModelShardEngine` is the second traced-code twin of the plain
:class:`~cruise_control_tpu.analyzer.engine.Engine` (beside
``parallel.mesh._ShardStepEngine``, which shards the CANDIDATE axis and
replicates the model).  Here the MODEL itself is a data axis: every
replica-indexed array (placements, per-replica loads/bytes, topic/rack id
columns) and every partition-indexed array (the partition->replica member
table, the per-partition rack-count cells) is partitioned over MODEL_AXIS
in contiguous row blocks, so per-chip memory for the model state and the
per-step O(R)/O(P) FLOPs drop ~1/n.  Broker/host/topic-indexed aggregates
and all scalars stay replicated — they are O(B), tiny next to O(R).

Layout contract
---------------
The padded global shape has R and P rounded up to multiples of n
(``models.sharding.shard_multiple_shape``); shard ``i`` owns the
contiguous GLOBAL rows ``[i*Rl, (i+1)*Rl)`` / ``[i*Pl, (i+1)*Pl)`` of the
replica / partition axes.  Array VALUES keep global ids (a shard-local
``replica_partition`` row still holds a global partition id), so all
cross-row references work unchanged.

RNG and the ownership gather
----------------------------
Every candidate draw comes from the REPLICATED key, so all shards hold
identical (global) row ids each step.  Row gathers at global ids resolve
by ownership: each shard translates ids into its local range, gathers the
rows it owns, zeros the rest, and ONE ``psum`` over MODEL_AXIS assembles
the full bundle (exactly one shard owns each id; ``x + 0`` is exact for
the non-negative floats involved, and integer/bool columns ride as i32).
Everything between the seams — feasibility, delta math, Metropolis
acceptance, conflict resolution — is replicated math over the K candidate
columns and is inherited from the plain engine verbatim; `_step` itself
is Engine._step, untouched.

Scatter-side: `_apply` already takes global ids in its payload, so the
twin only passes its row offsets/extents — rows owned by other shards
fall out of range and drop, broker/host/topic aggregates (replicated)
absorb every row on every shard.  No collective in the scatter.

Byte parity: psum-assembled row bundles are exactly the plain engine's
gathers (ownership makes each sum a single non-zero term), and the
replicated acceptance math consumes identical inputs — so placements are
byte-identical to the replicated-mesh/plain engine whenever the psum'd
OBJECTIVE partial sums are exact, which integer-quantized loads guarantee
(tests/test_model_shard.py) and float loads track to ulp-level rounding.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.engine import Engine, _uniform_idx

MODEL_AXIS = "model"

__all__ = ["ShardPlan", "_ModelShardEngine", "MODEL_AXIS", "stable_grouped_order"]

_INT32_SPAN = 1 << 31


def stable_grouped_order(seg: jax.Array, n_keys: int) -> jax.Array:
    """Stable argsort of integer keys built from SINGLE-operand sorts.

    Drop-in for ``jnp.argsort(seg)`` when ``seg`` holds keys in
    ``[0, n_keys)``.  ``jnp.argsort`` lowers to a variadic (two-operand)
    ``lax.sort``; on the pinned jax/XLA build the CPU backend miscompiles
    variadic sorts of shard-varying operands inside a
    ``shard_map(check_rep=False)`` program whose results feed a
    ``lax.scan`` — every device silently receives device 0's sort output
    (tests/test_model_shard.py::test_variadic_sort_miscompile_guard keeps
    a minimal repro pinned).  Single-operand sorts are unaffected, so the
    grouped order is recovered from ``sort(key * L + index)``: the packed
    value stays inside int32 by sorting in chunks of ``L`` rows and
    splicing the chunks with histogram prefix sums (a counting-sort
    composition — stable across chunks because chunk ``c``'s rows keep a
    lower rank than chunk ``c+1``'s within every key bucket).
    """
    n = int(seg.shape[0])
    if n == 0:
        return jnp.zeros(0, jnp.int32)
    # one extra bucket for chunk padding; packed max is nk * L - 1 < 2^31
    nk = n_keys + 1
    chunk = min(n, max(1, _INT32_SPAN // nk))
    n_chunks = -(-n // chunk)
    padded = n_chunks * chunk
    seg_c = jnp.concatenate(
        [seg.astype(jnp.int32), jnp.full(padded - n, n_keys, jnp.int32)]
    ).reshape(n_chunks, chunk)
    packed = jnp.sort(seg_c * chunk + jnp.arange(chunk, dtype=jnp.int32)[None, :])
    keys = packed // chunk  # [C, L] per-chunk sorted keys
    idx = packed % chunk  # [C, L] per-chunk stable order
    if n_chunks == 1:
        return idx[0, :n]
    hist = jax.vmap(
        lambda s: jax.ops.segment_sum(jnp.ones(chunk, jnp.int32), s, num_segments=nk)
    )(seg_c)  # [C, nk]
    # rank of chunk c's bucket-b rows among ALL bucket-b rows: rows of the
    # same bucket on earlier chunks come first, then in-chunk sorted order
    before_chunks = jnp.concatenate(
        [jnp.zeros((1, nk), jnp.int32), jnp.cumsum(hist[:-1], 0, dtype=jnp.int32)]
    )  # [C, nk] exclusive over chunks
    bucket_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(hist.sum(0))[:-1].astype(jnp.int32)]
    )  # [nk] global exclusive over buckets
    in_chunk_start = jnp.concatenate(
        [jnp.zeros((n_chunks, 1), jnp.int32), jnp.cumsum(hist, 1, dtype=jnp.int32)[:, :-1]],
        axis=1,
    )  # [C, nk] exclusive over buckets, per chunk
    q = jnp.arange(chunk, dtype=jnp.int32)[None, :]
    within = q - jnp.take_along_axis(in_chunk_start, keys, axis=1)
    pos = (
        bucket_start[keys] + jnp.take_along_axis(before_chunks, keys, axis=1) + within
    )
    gid = idx + (jnp.arange(n_chunks, dtype=jnp.int32) * chunk)[:, None]
    # padding rows land in bucket n_keys at pos >= n and drop
    return (
        jnp.zeros(n, jnp.int32).at[pos.reshape(-1)].set(gid.reshape(-1), mode="drop")
    )


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "broker_cdf", "order", "start", "count", "count_local", "below",
        "replica_cost", "lead_cost",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """SamplingPlan's model-sharded counterpart.

    The broker categorical (`broker_cdf`) and the movement prices are
    replicated scalars/O(B) — identical to the plain plan.  The grouped
    replica index is shard-local (`order`/`start`/`count_local` cover this
    shard's Rl rows), plus two replicated O(B) columns that make the
    replicated two-stage draw resolvable by ownership: `count` (GLOBAL
    per-broker replica counts — the draw `j ~ U[0, count)` must see the
    global group size to match the plain engine's stream) and `below`
    (how many of broker b's replicas live on lower-indexed shards: the
    stable argsort of contiguous row blocks concatenates per-shard groups
    in shard order, so global group position j lives on the shard where
    ``below[b] <= j < below[b] + count_local[b]`` at local offset
    ``j - below[b]``)."""

    broker_cdf: jax.Array  # f32[B] inclusive cumsum of broker probabilities
    order: jax.Array  # i32[Rl] LOCAL replica ids grouped by broker
    start: jax.Array  # i32[B] group offsets into order (local)
    count: jax.Array  # i32[B] GLOBAL replicas per broker (psum'd)
    count_local: jax.Array  # i32[B] this shard's replicas per broker
    below: jax.Array  # i32[B] replicas per broker on lower-indexed shards
    replica_cost: jax.Array  # f32 scalar (replicated)
    lead_cost: jax.Array  # f32 scalar (replicated)


class _ModelShardEngine(Engine):
    """Engine twin with the model sharded over MODEL_AXIS.

    Shares the parent engine's entire ``__dict__`` (weights, config,
    statics layout) exactly like ``_ShardStepEngine`` — only the
    class-level `_model_axis` marker and the row-provider seams differ,
    so the step/round/anneal schedule is inherited verbatim and cannot
    diverge from the single-device semantics."""

    #: class-level (NOT instance) so the shared __dict__ never leaks the
    #: axis name into the plain engine or the candidate-sharding twin
    _model_axis = MODEL_AXIS

    def __init__(self, engine: Engine, n_shards: int):  # noqa: D401
        # deliberately NOT calling Engine.__init__: traced-code twin
        self.__dict__.update(engine.__dict__)
        R, P = engine.shape.R, engine.shape.P
        if R % n_shards or P % n_shards:
            raise ValueError(
                f"model sharding needs R={R}, P={P} divisible by "
                f"n_shards={n_shards} (pad with shard_multiple_shape)"
            )
        self._n_shards = n_shards
        self._r_local = R // n_shards
        self._p_local = P // n_shards
        self._max_rf = int(engine.statics.part_replicas.shape[1])

    # ------------------------------------------------------------------
    # the ownership gather
    # ------------------------------------------------------------------

    def _axis_idx(self):
        return jax.lax.axis_index(self._model_axis)

    def _own_take(self, cols: dict, ids, local_n: int) -> dict:
        """Gather rows at GLOBAL ids from shard-local column arrays.

        ids may have any shape; each column is [local_n, ...].  Exactly
        one shard owns each id (contiguous row blocks), so the masked
        local gathers sum to the exact global gather under ONE bundled
        psum.  Bool columns ride as i32 (psum rejects bools)."""
        li = ids - self._axis_idx() * local_n
        own = (li >= 0) & (li < local_n)
        lc = jnp.clip(li, 0, local_n - 1)
        picked = {}
        bools = set()
        for f, a in cols.items():
            v = a[lc]
            if v.dtype == jnp.bool_:
                bools.add(f)
                v = v.astype(jnp.int32)
            m = own if v.ndim == own.ndim else own.reshape(
                own.shape + (1,) * (v.ndim - own.ndim)
            )
            picked[f] = jnp.where(m, v, jnp.zeros((), v.dtype))
        out = jax.lax.psum(picked, self._model_axis)
        return {f: (v.astype(bool) if f in bools else v) for f, v in out.items()}

    # ---- row-provider seam overrides (see Engine for the contracts) ----

    def _take_rows(self, sx, carry, ids, fields):
        cols = {f: self._row_source(sx, carry, f) for f in fields}
        return self._own_take(cols, ids, self._r_local)

    def _take_members(self, sx, part):
        return self._own_take(
            {"m": sx.part_replicas}, part, self._p_local
        )["m"]

    def _member_field(self, sx, carry, members, field, fill):
        src = {field: self._row_source(sx, carry, field)}
        vals = self._own_take(
            src, jnp.minimum(members, self.shape.R - 1), self._r_local
        )[field]
        return jnp.where(members < self.shape.R, vals, fill)

    def _rack_cell(self, carry, part, rack):
        lp = part - self._axis_idx() * self._p_local
        own = (lp >= 0) & (lp < self._p_local)
        v = carry.part_rack_count[jnp.clip(lp, 0, self._p_local - 1), rack]
        return jax.lax.psum(
            jnp.where(own, v, 0), self._model_axis
        ).astype(jnp.float32)

    # ------------------------------------------------------------------
    # carry layout / sampling plan
    # ------------------------------------------------------------------

    def _prc_shape(self):
        # part_rack_count rows are shard-local (matches the psum_scatter
        # output of the sharded compute_aggregates)
        return (self._p_local, self.shape.num_racks)

    def _plan_build(self, sx, carry, probs, unit):
        st = sx.state
        B = self.shape.B
        Rl = self._r_local
        seg = jnp.where(st.replica_valid, carry.replica_broker, B)  # [Rl]
        count_local = jax.ops.segment_sum(
            jnp.ones(Rl, jnp.int32), seg, num_segments=B + 1
        )[:B]
        count = jax.lax.psum(count_local, self._model_axis)
        # per-broker replicas on LOWER-indexed shards: the shard-order
        # prefix sum of the gathered local counts
        all_counts = jax.lax.all_gather(count_local, self._model_axis)  # [n, B]
        i = self._axis_idx()
        below = jnp.where(
            jnp.arange(self._n_shards)[:, None] < i, all_counts, 0
        ).sum(0)
        start = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(count_local)[:-1].astype(jnp.int32)]
        )
        return ShardPlan(
            broker_cdf=jnp.cumsum(probs),
            order=stable_grouped_order(seg, B + 1),
            start=start,
            count=count,
            count_local=count_local,
            below=below,
            replica_cost=self.config.replica_move_cost * unit,
            lead_cost=self.config.leadership_move_cost * unit,
        )

    def _sample_sources(self, sx, key, n, plan):
        """Replicated draws, ownership-resolved plan lookups.

        The uniform draws and the two-stage (broker, j) draws are the
        plain engine's replicated streams verbatim (global `count` feeds
        the j draw).  The grouped-order lookup runs shard-local: the
        owner of global group position j reads its local `order` row and
        re-offsets to the global id; a psum assembles the result (stable
        argsort over contiguous ownership blocks == the global grouped
        order, so the stream is bit-identical to the plain engine's)."""
        k1, k3, k4, k5 = jax.random.split(key, 4)
        n_imp = (
            int(round(n * self.config.importance_fraction)) if plan is not None else 0
        )
        r = _uniform_idx(k1, (n - n_imp,), sx.n_source)
        if n_imp:
            u = jax.random.uniform(k3, (n_imp,))
            bsel = jnp.clip(
                jnp.searchsorted(plan.broker_cdf, u, side="right"),
                0, sx.n_brokers - 1,
            ).astype(jnp.int32)
            j = (
                jax.random.uniform(k4, (n_imp,)) * plan.count[bsel]
            ).astype(jnp.int32)
            lj = j - plan.below[bsel]
            own = (lj >= 0) & (lj < plan.count_local[bsel])
            r_loc = plan.order[
                jnp.clip(plan.start[bsel] + lj, 0, self._r_local - 1)
            ]
            r_imp = jax.lax.psum(
                jnp.where(own, r_loc + self._axis_idx() * self._r_local, 0),
                self._model_axis,
            )
            fallback = _uniform_idx(k5, (n_imp,), sx.n_source)
            r_imp = jnp.where(plan.count[bsel] > 0, r_imp, fallback)
            r = jnp.concatenate([r, r_imp])
        return r

    def _apply(self, sx, carry, sv_r, payr, sv_l, payl, **_):
        """Payload ids are global; placement scatters translate to this
        shard's rows (others drop), replicated aggregates absorb all rows.
        No collective."""
        i = self._axis_idx()
        return Engine._apply(
            self, sx, carry, sv_r, payr, sv_l, payl,
            r_offset=i * self._r_local, p_offset=i * self._p_local,
            r_size=self._r_local, p_size=self._p_local,
        )

    # ------------------------------------------------------------------
    # collective accounting (analytic: the psum schedule is static)
    # ------------------------------------------------------------------

    def psum_bytes_per_step(self) -> int:
        """Per-device bytes reduced over MODEL_AXIS in one anneal step.

        Counted analytically from the seam-call schedule (every bundle
        shape is a static function of the candidate split / max_rf /
        config flags, so no tracing is needed): source ownership
        resolutions, the per-kind row bundles (6 resp. 5 scalar columns +
        two [K, 4] load columns each), member tables and member-column
        gathers, rack cells, and the assemble-stage topic/disk gathers.
        All exchanged leaves are 4-byte (i32/f32; bools ride as i32)."""
        cfg = self.config
        mrf = self._max_rf
        pref = 1 if self.w.pref_leader != 0.0 else 0
        rcost = 1 if cfg.replica_move_cost else 0
        lcost = 1 if cfg.leadership_move_cost else 0
        Kr, Ks, Kl = self.K_r, self.K_s, self.K_l
        units = 0
        if Kr:
            units += int(round(Kr * cfg.importance_fraction))  # source resolve
            if cfg.intra_broker:
                units += Kr * (14 + rcost)  # row bundle (no members/racks)
            else:
                if cfg.prior_enabled:
                    units += Kr  # prior-dest topic rows
                units += Kr * (14 + pref + rcost)  # row bundle
                units += 2 * Kr * mrf  # members + member brokers
                units += 2 * Kr  # rack cells
        if Ks:
            units += int(round(Ks * cfg.importance_fraction))
            units += 2 * Ks * (14 + pref + rcost)  # both draw lanes, one bundle
            units += 4 * Ks * mrf  # two member tables + member brokers
            units += 4 * Ks  # four rack cells
        if Kl:
            units += Kl * (13 + pref + lcost)  # target rows
            units += 2 * Kl * mrf  # members + member leader flags
            units += Kl * (10 + pref + lcost)  # current-leader rows
            units += 2 * Kl  # assemble d_f/d_t
        units += Kr + 2 * Ks  # assemble topic column over r_ext
        return 4 * units

    def psum_bytes_per_round(self) -> int:
        """psum_bytes_per_step * steps + the per-round O(B + T·B + P·racks)
        exchanges: the aggregate refresh's psum'd segment sums, the
        part_rack_count reduce-scatter, and the plan rebuild's count
        psum/all_gather.  Scalar gsums (objective, goal violations) are
        counted as a flat noise term."""
        sh = self.shape
        refresh = (
            (sh.B + 1) * 8  # broker_load[,4] + 4 scalar broker columns
            + (sh.num_topics * sh.B + 1)
            + (sh.B * sh.max_disks_per_broker + 1)
            + sh.P * sh.num_racks  # reduce-scatter exchange volume
        )
        plan = sh.B * (1 + self._n_shards)  # count psum + all_gather
        scalars = 64
        return (
            self.psum_bytes_per_step() * self.config.steps_per_round
            + 4 * (refresh + plan + scalars)
        )
