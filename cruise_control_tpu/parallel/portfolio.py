"""Multi-device parallel optimization: SA restart portfolio.

The reference parallelizes only across *cached proposal computations*
(reference analyzer/GoalOptimizer.java:100-107 precompute thread pool); a
single optimization is strictly sequential.  On TPU the restart axis is
free parallelism: independent annealing chains with different RNG seeds
race over the mesh to the best objective.  SA restart portfolios dominate
single long chains at equal device-seconds, and the axis scales to any
mesh shape.

``portfolio_run`` is the explicit-schedule entry point (the caller hands a
[rounds, steps] temperature schedule); it delegates to the shared mesh
engine layer (parallel/mesh.py) with a ``Mesh((restart=n, model=1))``
layout — one chain per device, every round device-resident, one
winner-selection sync.  The shard_map/collective plumbing that used to
live here is parallel/mesh.py, shared verbatim with sharded.py and
grid.py.

This module is mesh-shape agnostic: tests run it on an 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``), production on a TPU slice.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from cruise_control_tpu.analyzer.engine import Engine
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.parallel.mesh import (
    RESTART_AXIS,
    MeshEngine,
    default_mesh,
)

__all__ = ["RESTART_AXIS", "default_mesh", "portfolio_run"]


@device_op("portfolio.run")
def portfolio_run(
    engine: Engine,
    mesh: Mesh,
    temps: jax.Array,
    *,
    seed: int = 0,
) -> tuple[ClusterState, dict]:
    """Run one annealing chain per mesh device; return the best final state.

    temps: f32[S] (one round) or f32[rounds, S] (multi-round).  Multi-round
    chains stay ENTIRELY device-resident — each chain refreshes its
    aggregates and rebuilds its sampling plan between rounds in-graph,
    matching the fused single-device execution model: one dispatch, one
    winner fetch, zero per-round host syncs.

    Wraps the caller's EXISTING engine (MeshEngine.from_engine): its
    statics are re-placed as mesh-replicated arrays, so arrays an earlier
    single-device run committed to one device can never poison the mesh
    program (the r4 portfolio devices-mismatch failure mode); the caller's
    engine is never mutated.
    """
    me = MeshEngine.from_engine(engine, mesh)
    state, info = me.run_schedule(temps, seed=seed)
    return state, info
