"""Multi-device parallel optimization: sharded SA restart portfolio.

The reference parallelizes only across *cached proposal computations*
(reference analyzer/GoalOptimizer.java:100-107 precompute thread pool); a
single optimization is strictly sequential.  On TPU we get two axes:

  1. candidate axis — K moves evaluated per step inside one device's
     vectorized step (engine.py);
  2. restart axis — independent annealing chains with different RNG seeds,
     sharded over the device mesh with `shard_map`, racing to the best
     objective; the winner is selected with an `all_gather` + argmin over
     ICI.  SA restart portfolios dominate single long chains at equal
     device-seconds, and the axis scales to any mesh shape (pure DP —
     SURVEY §2.6 "data-parallel over candidate plans").

This module is mesh-shape agnostic: tests run it on an 8-device CPU mesh
(`--xla_force_host_platform_device_count=8`), production on a TPU slice.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cruise_control_tpu.analyzer.engine import Engine, EngineCarry
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.models.state import ClusterState

RESTART_AXIS = "restart"


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (RESTART_AXIS,))


@device_op("portfolio.run")
def portfolio_run(
    engine: Engine,
    mesh: Mesh,
    temps: jax.Array,
    *,
    seed: int = 0,
) -> tuple[ClusterState, dict]:
    """Run one annealing chain per mesh device; return the best final state.

    temps: f32[S] (one round) or f32[rounds, S] (multi-round).  Multi-round
    chains stay ENTIRELY device-resident — each chain refreshes its
    aggregates and rebuilds its sampling plan between rounds in-graph
    (engine._round_prep_impl), matching the fused single-device execution
    model: one dispatch, one winner fetch, zero per-round host syncs.
    """
    temps = jnp.asarray(temps, jnp.float32)
    if temps.ndim == 1:
        temps = temps[None]
    n = mesh.devices.size
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    run_round = engine._make_scan()
    statics = engine.statics

    def chain_fn(key, sx, carry: EngineCarry, plan):
        # per-device chain: same initial carry, device-specific key
        key = key.reshape(-1)[0:2].reshape(2)  # shard_map passes [1, 2]
        carry = dataclasses.replace(carry, key=key)

        def round_body(cp, t_row):
            c, p = cp
            c, stats = run_round(sx, c, t_row, p)
            # between-rounds program: wash float drift, rebuild the
            # chain-specific sampling plan — chains diverge, so the plan
            # must too (the shared init plan only seeds round 0)
            c, p, _cheap = engine._round_prep_impl(sx, c)
            return (c, p), stats["accepted"].sum()

        (carry, _), _accepted = jax.lax.scan(round_body, (carry, plan), temps)
        obj = _sa_objective(engine, sx, carry)
        # race resolution: gather objectives, broadcast the winner's placement
        objs = jax.lax.all_gather(obj, RESTART_AXIS)  # [n]
        best = jnp.argmin(objs)
        placement = jnp.stack(
            [
                carry.replica_broker,
                carry.replica_disk,
                carry.replica_is_leader.astype(carry.replica_broker.dtype),
            ]
        )
        all_placements = jax.lax.all_gather(placement, RESTART_AXIS)  # [n, 3, R]
        winner = all_placements[best]
        return winner[None], objs[None]

    try:
        from jax import shard_map

        smap = shard_map(
            chain_fn,
            mesh=mesh,
            in_specs=(P(RESTART_AXIS), P(), P(), P()),
            out_specs=(P(RESTART_AXIS), P(RESTART_AXIS)),
            check_vma=False,
        )
    except (ImportError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map

        smap = shard_map(
            chain_fn,
            mesh=mesh,
            in_specs=(P(RESTART_AXIS), P(), P(), P()),
            out_specs=(P(RESTART_AXIS), P(RESTART_AXIS)),
            check_rep=False,
        )
    sharded = jax.jit(smap)
    carry0 = engine.init_carry(jax.random.PRNGKey(seed))
    plan0 = engine._jit_plan(statics, carry0)
    winners, objs = sharded(keys, statics, carry0, plan0)
    # out axis stacks each device's all_gather copy: [n_dev, n_chains]
    objs = np.asarray(objs).reshape(n, n)[0]
    # every device computed the same winner; take device 0's copy
    w = jax.device_get(winners)[0]
    final_carry = dataclasses.replace(
        carry0,
        replica_broker=jnp.asarray(w[0]),
        replica_disk=jnp.asarray(w[1]),
        replica_is_leader=jnp.asarray(w[2]).astype(bool),
    )
    state = engine.carry_to_state(final_carry)
    return state, {"objectives": objs, "n_chains": n}


def _sa_objective(engine: Engine, sx, carry: EngineCarry):
    """Scalar SA objective from carry aggregates (traceable, collective-free)."""
    return engine.carry_objective(sx, carry)
