"""Model-sharded optimization: replica/partition axes split over the mesh.

The restart portfolio (portfolio.py) is pure data parallelism — every device
holds the WHOLE cluster model.  At reference scale that is fine (200k
partitions ≈ tens of MB), but the design must also cover models that exceed
one chip's HBM (SURVEY §2.6: "replica-axis sharding is our sequence
parallelism"; §7 M6).  This module shards the MODEL itself:

  * The replica axis [R] and partition axis [P] are sharded across the mesh,
    with a partition-grouped layout so every replica of a partition lives on
    the same shard (leadership transfers and rack counts stay shard-local).
  * The small broker/host/topic/disk aggregates ([B]-sized) are REPLICATED;
    every device applies the same aggregate updates so they never diverge.
  * Each step, every device samples candidates from ITS replica shard and
    evaluates exact objective deltas locally (the broker aggregates it needs
    are replicated).  Candidate metadata — not replica data — is exchanged
    with one `all_gather` over the mesh axis, conflict resolution runs
    identically everywhere, and each shard scatters only the placement rows
    it owns (`Engine._apply` with r_offset/p_offset translation).
  * Aggregate re-derivation (`refresh`) computes per-shard partial
    segment-sums and `psum`s them over the mesh — the objective's partial
    reductions ride ICI, never the host.

Communication per step is O(num_candidates) floats — independent of R — so
the design scales to arbitrarily large cluster models at constant per-step
comm volume.  Candidate throughput also scales: n devices evaluate
n × num_candidates moves per step.

Swap partners are sampled within a shard (a swap across shards would need a
second placement exchange); relocations and leadership transfers are
unrestricted, so cross-shard mass still moves freely — shards partition the
*partition id space*, not brokers.

Shape bucketing (models.state.ShapeBucketPolicy): when constructed with a
`bucket` policy, the input model is padded to its shape bucket BEFORE the
shard split, so the per-device shard shapes derive from the bucketed
global shape and survive topology churn (rebind instead of recompile),
and exact-vs-bucketed builds of the same cluster shard — and anneal —
identically.  The optimized placement is always reassembled onto the
caller's original (unpadded) replica axis.

Reference analog: none — the reference's optimizer is a single-threaded Java
loop over one in-heap model (analyzer/goals/AbstractGoal.java:66-107).  This
is the TPU-native scale-out story for it.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from cruise_control_tpu.analyzer.engine import (
    Engine,
    EngineCarry,
    OptimizerConfig,
    build_statics,
    partition_replica_table,
)
from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.common.resources import NUM_RESOURCES
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import (
    ClusterShape,
    ClusterState,
    ShapeBucketPolicy,
)

MODEL_AXIS = "model"


def model_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (MODEL_AXIS,))


def _shard_map(fn, mesh, in_specs, out_specs):
    try:
        from jax import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)
    except (ImportError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map

        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_rep=False)


def _unstack(tree):
    """[1, ...] shard_map block -> local pytree."""
    return jax.tree.map(lambda x: x[0], tree)


def _restack(tree):
    return jax.tree.map(lambda x: x[None], tree)


def _tree_stack(trees):
    return jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *trees)


@dataclasses.dataclass(frozen=True)
class ShardLayout:
    """Host-side partition-grouped sharding of a ClusterState.

    orig_index[i, j] is the original replica id behind shard i's local slot
    j, or -1 for padding — the inverse map used to reassemble the optimized
    placement in the original replica order.
    """

    n_shards: int
    R_local: int
    P_local: int
    max_rf: int
    orig_index: np.ndarray  # i32[n, R_local]
    local_states: list  # per-shard ClusterState (numpy-backed)


def build_layout(
    state: ClusterState,
    n: int,
    *,
    bucket: ShapeBucketPolicy | None = None,
) -> ShardLayout:
    """Split `state` into n partition-aligned shards.

    Partitions [i*P_local, (i+1)*P_local) and every replica of those
    partitions land on shard i; each shard is padded to a uniform R_local so
    the stacked arrays are rectangular.  R_local is data-dependent (the
    fullest shard's replica count), so it is rounded up to a geometric
    bucket: with the global shape itself bucketed at model-build time, the
    per-device shard shapes then also stay stable under topology churn and
    `rebind()` keeps hitting the compiled sharded programs.
    """
    s = state.shape
    P_local = -(-s.P // n)  # ceil
    valid = np.asarray(state.replica_valid)
    part = np.asarray(state.replica_partition)
    shard_of = np.where(valid, part // P_local, -1)
    counts = np.bincount(shard_of[valid], minlength=n)
    R_local = max(8, int(counts.max()))
    if bucket is not None and bucket.enabled:
        R_local = bucket.bucket(R_local)
    R_local = int(-(-R_local // 8) * 8)  # pad to /8
    counts_all = np.bincount(part[valid], minlength=s.P)
    max_rf = max(1, int(counts_all.max())) if counts_all.size else 1

    local_shape = ClusterShape(
        num_replicas=R_local,
        num_brokers=s.B,
        num_partitions=P_local,
        num_topics=s.num_topics,
        num_racks=s.num_racks,
        num_hosts=s.num_hosts,
        max_disks_per_broker=s.max_disks_per_broker,
    )
    orig_index = np.full((n, R_local), -1, np.int64)
    locals_: list[ClusterState] = []
    repl_fields = [
        "replica_broker", "replica_partition", "replica_topic", "replica_pos",
        "replica_is_leader", "replica_valid", "replica_orig_broker",
        "replica_offline", "replica_disk", "replica_load_leader",
        "replica_load_follower",
    ]
    for i in range(n):
        sel = np.nonzero(shard_of == i)[0]
        k = sel.size
        orig_index[i, :k] = sel
        kw = {}
        for f in repl_fields:
            src = np.asarray(getattr(state, f))
            pad_shape = (R_local,) + src.shape[1:]
            dst = np.zeros(pad_shape, src.dtype)
            dst[:k] = src[sel]
            kw[f] = dst
        kw["replica_partition"] = kw["replica_partition"] - np.int32(i * P_local)
        kw["replica_partition"][k:] = 0
        kw["replica_valid"][k:] = False
        locals_.append(
            dataclasses.replace(
                state,
                shape=local_shape,
                **{f: jnp.asarray(v) for f, v in kw.items()},
            )
        )
    return ShardLayout(
        n_shards=n, R_local=R_local, P_local=P_local, max_rf=max_rf,
        orig_index=orig_index, local_states=locals_,
    )


class ShardedEngine:
    """Engine wrapper that runs ONE annealing chain over a sharded model.

    Reuses Engine's candidate/delta/apply machinery on shard-local views; the
    cross-shard glue (gather, global selection, psum'd refresh) lives here.
    """

    def __init__(
        self,
        state: ClusterState,
        chain: GoalChain,
        mesh: Mesh | None = None,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig = OptimizerConfig(),
        bucket: ShapeBucketPolicy | None = None,
    ):
        """bucket: optional ShapeBucketPolicy (the GoalOptimizer passes the
        service policy).  When set, the input model is padded to its shape
        bucket BEFORE the shard split, so (a) the per-device shard shapes
        derive from the bucketed global shape and stay stable under
        topology churn, and (b) an exact and a bucketed build of the same
        cluster shard identically — the trajectory-parity guarantee of the
        single-device engine carries over to the sharded path.  The final
        placement is always reassembled onto the ORIGINAL (unpadded)
        state."""
        self.mesh = mesh if mesh is not None else model_mesh()
        # number of MODEL shards — on a 2D (restart, model) mesh this is the
        # model-axis extent, not the device count
        self.n = int(self.mesh.shape[MODEL_AXIS])
        self._bucket = bucket if bucket is not None and bucket.enabled else None
        self.global_state = state
        self.layout = build_layout(self._padded(state), self.n, bucket=self._bucket)
        self.P_total = self.layout.P_local * self.n
        # local-shape engine: candidate generation + apply run per shard
        self.engine = Engine(
            self.layout.local_states[0], chain, constraint, options, config
        )
        self._bind(state, self.layout, options)
        self._build_jits()

    def _padded(self, state: ClusterState) -> ClusterState:
        if self._bucket is None:
            return state
        from cruise_control_tpu.models.builder import pad_state

        return pad_state(state, self._bucket.bucket_shape(state.shape))

    def _bind(self, state: ClusterState, layout: ShardLayout,
              options: OptimizationOptions) -> None:
        """Point the engine at a model generation: stacked per-shard statics
        from `layout`, honoring `options` (shared by __init__ and rebind so
        the two can never diverge)."""
        self.global_state = state
        self.layout = layout
        self._options = options
        n_valid_global = jnp.asarray(
            max(1.0, float(np.asarray(state.replica_valid).sum())), jnp.float32
        )
        statics_list = []
        for ls in layout.local_states:
            sx = build_statics(ls, options)
            sx = dataclasses.replace(
                sx,
                n_valid=n_valid_global,
                part_replicas=jnp.asarray(
                    partition_replica_table(ls, max_rf=layout.max_rf)
                ),
            )
            statics_list.append(sx)
        self.statics = _tree_stack(statics_list)

    def release(self) -> None:
        """Drop device buffers on engine-cache eviction.

        The inner Engine releases its engine-derived arrays; the shard-local
        states and stacked statics are only DE-REFERENCED — their broker-axis
        fields alias the caller's global ClusterState (and, unbucketed, the
        replica fields too), so explicit delete() here would destroy arrays
        the caller still holds (result.state_before, sibling engines).  The
        engine-private shard arrays free via refcount as soon as these refs
        drop.  The engine is unusable afterwards."""
        self.engine.release()
        self.statics = None
        self.layout = None
        self.global_state = None

    def rebind(self, state: ClusterState, options: OptimizationOptions = DEFAULT_OPTIONS):
        """Swap in a new model generation without recompiling.

        The shard layout's local shapes (R_local/P_local/max_rf) are
        data-dependent; when they match the compiled ones the jitted
        programs are reused, otherwise a ValueError tells the caller to
        build a fresh engine (mirrors Engine.rebind's shape check).  With
        a bucket policy the layout derives from the BUCKETED global shape,
        so generations inside a bucket always match."""
        lay = build_layout(self._padded(state), self.n, bucket=self._bucket)
        old = self.layout
        if (lay.R_local, lay.P_local, lay.max_rf) != (
            old.R_local, old.P_local, old.max_rf
        ):
            raise ValueError(
                "shard layout changed "
                f"{(old.R_local, old.P_local, old.max_rf)} -> "
                f"{(lay.R_local, lay.P_local, lay.max_rf)}; build a new engine"
            )
        self._bind(state, lay, options)
        return self

    def _build_jits(self):
        spec_in = P(MODEL_AXIS)
        self._jit_init = jax.jit(
            _shard_map(
                self._init_fn, self.mesh,
                in_specs=(spec_in, spec_in), out_specs=spec_in,
            )
        )
        self._jit_round = jax.jit(
            _shard_map(
                self._round_fn, self.mesh,
                in_specs=(spec_in, spec_in, P()), out_specs=(spec_in, spec_in),
            )
        )
        # fused multi-round program (engine.py execution model): ALL rounds
        # chain on device — the per-round host dispatch+sync of the legacy
        # loop disappears, and the carry is donated so each restart/model
        # shard holds one placement copy in HBM
        self._jit_run = jax.jit(
            _shard_map(
                self._run_fn, self.mesh,
                in_specs=(spec_in, spec_in, P()), out_specs=(spec_in, spec_in),
            ),
            donate_argnums=(1,),
        )
        self._jit_obj = jax.jit(
            _shard_map(
                self._obj_fn, self.mesh,
                in_specs=(spec_in, spec_in), out_specs=spec_in,
            )
        )

    # ---- traced per-shard bodies (run inside shard_map) ----

    def _sharded_refresh(self, sx, carry: EngineCarry) -> EngineCarry:
        """Re-derive aggregates: local partial segment-sums + psum over mesh."""
        eng = self.engine
        state = eng.carry_to_state(carry, sx)
        agg = compute_aggregates(state)  # partials (local replicas, full B axis)
        psum = lambda x: jax.lax.psum(x, MODEL_AXIS)  # noqa: E731
        broker_load = psum(agg.broker_load)
        hseg = jnp.where(
            state.broker_valid, state.broker_host, eng.shape.num_hosts
        )
        host_load = jax.ops.segment_sum(
            broker_load, hseg, num_segments=eng.shape.num_hosts + 1
        )[: eng.shape.num_hosts]
        return dataclasses.replace(
            carry,
            broker_load=broker_load,
            broker_replica_count=psum(agg.broker_replica_count),
            broker_leader_count=psum(agg.broker_leader_count),
            broker_potential_nw_out=psum(agg.broker_potential_nw_out),
            broker_leader_bytes_in=psum(agg.broker_leader_bytes_in),
            broker_topic_count=psum(agg.broker_topic_count),
            part_rack_count=agg.part_rack_count,  # partition axis: shard-local
            disk_load=psum(agg.disk_load),
            host_load=host_load,
        )

    def _sharded_objective(self, sx, carry: EngineCarry):
        """carry_objective with the partition/replica partials psum'd."""
        eng = self.engine
        g = eng._globals(sx, carry)
        b = jnp.arange(eng.shape.B)
        terms = eng._broker_terms(
            sx, b,
            carry.broker_load, carry.broker_replica_count,
            carry.broker_leader_count, carry.broker_potential_nw_out,
            carry.broker_leader_bytes_in, g,
        ).sum()
        rack_local = jnp.maximum(carry.part_rack_count - 1, 0).sum().astype(jnp.float32)
        st = sx.state
        off_local = (
            st.replica_valid
            & ~(
                st.broker_alive[carry.replica_broker]
                & st.disk_alive[carry.replica_broker, carry.replica_disk]
            )
        ).sum().astype(jnp.float32)
        partials = jax.lax.psum(jnp.stack([rack_local, off_local]), MODEL_AXIS)
        terms += eng.w.rack * partials[0] / sx.n_valid
        terms += eng.w.offline * partials[1] / sx.n_valid
        terms += eng._tie_term(sx, g["pct_sum"], g["pct_sumsq"])
        return terms

    def _sharded_step(self, sx, carry: EngineCarry, temperature, plan):
        eng = self.engine
        idx = jax.lax.axis_index(MODEL_AXIS)
        r_off = idx * self.layout.R_local
        p_off = idx * self.layout.P_local

        key, k_r, k_s, k_l, k_u = jax.random.split(carry.key, 5)
        g = eng._globals(sx, carry)
        prop = eng._propose(sx, carry, k_r, k_s, k_l, g, plan)

        delta, feas = prop["delta"], prop["feas"]
        K = delta.shape[0]
        u = jax.random.uniform(k_u, (K,), minval=1e-12, maxval=1.0)
        accept = feas & (delta < -temperature * jnp.log(u) - 1e-12)

        # globalize replica/partition ids, then exchange candidate METADATA
        # (O(K) floats — never replica data) across the mesh
        payr = dict(prop["payr"])
        payl = {k: v for k, v in prop["payl"].items() if not isinstance(v, int)}
        payr["r"] = payr["r"] + r_off
        payr["part"] = payr["part"] + p_off
        payl["rf"] = payl["rf"] + r_off
        payl["rt"] = payl["rt"] + r_off

        gather = lambda x: jax.lax.all_gather(x, MODEL_AXIS, tiled=True)  # noqa: E731
        delta_all = gather(delta)
        accept_all = gather(accept)
        src_all = gather(prop["src"])
        dst_all = gather(prop["dst"])
        p1_all = gather(prop["part1"] + p_off)
        p2_all = gather(prop["part2"] + p_off)
        payr_all = {k: gather(v) for k, v in payr.items()}
        payl_all = {k: gather(v) for k, v in payl.items()}

        # identical global conflict resolution on every shard
        survive = eng._select(
            accept_all, delta_all, src_all, dst_all, p1_all, p2_all,
            num_parts=self.P_total,
        )
        nr, ns = prop["nr"], prop["ns"]
        sv = survive.reshape(self.n, K)
        sv_r_ext = jnp.concatenate(
            [sv[:, :nr], sv[:, nr: nr + ns], sv[:, nr: nr + ns]], axis=1
        ).reshape(-1)
        sv_l = sv[:, nr + ns:].reshape(-1)

        # replicated aggregates absorb ALL rows; placement scatters translate
        # to shard-local ids and foreign rows drop out of range
        carry = eng._apply(
            sx, carry, sv_r_ext, payr_all, sv_l, payl_all,
            r_offset=r_off, p_offset=p_off,
        )
        carry = dataclasses.replace(carry, key=key)
        stats = dict(
            accepted=survive.sum(),
            improving=(accept_all & (delta_all < 0)).sum(),
        )
        return carry, stats

    # ---- shard_map entry points (blocks have a leading axis of 1) ----

    def _unstack_carry(self, blk):
        """Carry block -> local pytree (GridEngine strips two axes)."""
        return _unstack(blk)

    def _restack_carry(self, tree):
        return _restack(tree)

    def _restack_stats(self, tree):
        return jax.tree.map(lambda x: x[None], tree)

    def _zero_carry(self, sx, key) -> EngineCarry:
        eng = self.engine
        st = sx.state
        B = eng.shape.B
        return EngineCarry(
            replica_broker=st.replica_broker,
            replica_is_leader=st.replica_is_leader,
            replica_disk=st.replica_disk,
            broker_load=jnp.zeros((B, NUM_RESOURCES), jnp.float32),
            broker_replica_count=jnp.zeros(B, jnp.int32),
            broker_leader_count=jnp.zeros(B, jnp.int32),
            broker_potential_nw_out=jnp.zeros(B, jnp.float32),
            broker_leader_bytes_in=jnp.zeros(B, jnp.float32),
            broker_topic_count=jnp.zeros((eng.shape.num_topics, B), jnp.int32),
            part_rack_count=jnp.zeros(
                (eng.shape.P, eng.shape.num_racks), jnp.int32
            ),
            disk_load=jnp.zeros((B, eng.shape.max_disks_per_broker), jnp.float32),
            host_load=jnp.zeros((eng.shape.num_hosts, NUM_RESOURCES), jnp.float32),
            key=key,
        )

    def _run_round(self, sx, carry: EngineCarry, temps):
        """One annealing round on local blocks: plan + scan + refresh."""
        eng = self.engine
        plan = eng._plan_impl(sx, carry)
        # reprice movement against the GLOBAL objective (the local plan's
        # pricing only saw this shard's rack/offline partials)
        unit = self._sharded_objective(sx, carry) / sx.n_valid
        plan = dataclasses.replace(
            plan,
            replica_cost=eng.config.replica_move_cost * unit,
            lead_cost=eng.config.leadership_move_cost * unit,
        )

        def body(c, t):
            return self._sharded_step(sx, c, t, plan)

        carry, stats = jax.lax.scan(body, carry, temps)
        return self._sharded_refresh(sx, carry), stats

    def _init_fn(self, sx_blk, keys_blk):
        sx = _unstack(sx_blk)
        carry = self._zero_carry(sx, keys_blk[0])
        return _restack(self._sharded_refresh(sx, carry))

    def _round_fn(self, sx_blk, carry_blk, temps):
        sx = _unstack(sx_blk)
        carry, stats = self._run_round(sx, self._unstack_carry(carry_blk), temps)
        return self._restack_carry(carry), self._restack_stats(stats)

    def _run_fn(self, sx_blk, carry_blk, temps2d):
        """Fused multi-round body: scan over rounds, each round = plan +
        step scan + psum'd refresh, all device-resident.  temps2d is the
        f32[rounds, steps] schedule; per-round scalar stats (accept count,
        SA objective) come back stacked so the host syncs ONCE."""
        sx = _unstack(sx_blk)
        carry = self._unstack_carry(carry_blk)

        def body(c, t_row):
            c, stats = self._run_round(sx, c, t_row)
            # per-round SA objective (carry sufficient-statistics, O(B +
            # R_local) + a 2-scalar psum — marginal next to the round's
            # step scan): GridEngine's winner selection reads the last
            # round's value and verbose histories read them all, with no
            # extra dispatch or sync for either
            return c, dict(
                accepted=stats["accepted"].sum(),
                objective=self._sharded_objective(sx, c),
            )

        carry, ys = jax.lax.scan(body, carry, temps2d)
        return self._restack_carry(carry), self._restack_stats(ys)

    def _obj_fn(self, sx_blk, carry_blk):
        obj = self._sharded_objective(_unstack(sx_blk), self._unstack_carry(carry_blk))
        return obj[None]

    # ---- host-side driver ----

    def _temp_schedule(self, t0_obj: float) -> np.ndarray:
        """f32[rounds, steps] host-built temperature schedule (same values
        the legacy per-round loop dispatches; last round T=0)."""
        cfg = self.engine.config
        temps = np.zeros((cfg.num_rounds, cfg.steps_per_round), np.float32)
        for rnd in range(cfg.num_rounds - 1):
            temps[rnd] = t0_obj * (cfg.temperature_decay**rnd)
        return temps

    @device_op("sharded.run")
    def run(self, *, verbose: bool = False):
        """Execute the annealing schedule over the sharded model.

        Default (fused_rounds): ONE device-resident program runs every
        round (plan + scan + psum'd refresh chained in-graph); the host
        syncs twice — the initial objective for the temperature scale, and
        the per-round scalar stats.  `fused_rounds=False` falls back to
        the legacy one-dispatch-per-round loop.
        """
        cfg = self.engine.config
        if not cfg.fused_rounds:
            return self._run_legacy(verbose=verbose)
        t_start = time.monotonic()
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), self.n)
        carry = self._jit_init(self.statics, keys)
        t0_obj = float(np.asarray(self._jit_obj(self.statics, carry))[0])  # sync 1
        t0_obj *= cfg.init_temperature_scale
        temps = self._temp_schedule(t0_obj)
        t_disp = time.monotonic()
        carry, ys = self._jit_run(self.statics, carry, jnp.asarray(temps))
        ys = jax.device_get(ys)  # sync 2: O(rounds) scalars, carry stays put
        t_sync = time.monotonic()
        accepted = np.asarray(ys["accepted"])[0]
        objectives = np.asarray(ys["objective"])[0]
        history = []
        for rnd in range(cfg.num_rounds):
            rec = dict(
                round=rnd,
                temperature=float(temps[rnd, 0]),
                accepted=int(accepted[rnd]),
            )
            if verbose:
                rec["objective"] = float(objectives[rnd])
            history.append(rec)
        history.append(dict(
            timing=True, fused=True, blocking_syncs=2,
            host_dispatch_s=round(t_disp - t_start, 6),
            device_s=round(t_sync - t_disp, 6),
        ))
        return self.final_state(carry), history

    def _run_legacy(self, *, verbose: bool = False):
        """Legacy per-round loop: one jitted round + one blocking stats
        sync per round (kept for parity testing and per-round debugging)."""
        cfg = self.engine.config
        t_start = time.monotonic()
        syncs = 0
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), self.n)
        carry = self._jit_init(self.statics, keys)
        t0_obj = float(np.asarray(self._jit_obj(self.statics, carry))[0])
        syncs += 1
        t0_obj *= cfg.init_temperature_scale
        history = []
        for rnd in range(cfg.num_rounds):
            t_round = (
                0.0 if rnd == cfg.num_rounds - 1
                else t0_obj * (cfg.temperature_decay**rnd)
            )
            temps = jnp.full((cfg.steps_per_round,), t_round, jnp.float32)
            carry, stats = self._jit_round(self.statics, carry, temps)
            rec = dict(
                round=rnd,
                temperature=t_round,
                accepted=int(np.asarray(stats["accepted"])[0].sum()),
            )
            syncs += 1
            if verbose:
                rec["objective"] = float(np.asarray(self._jit_obj(self.statics, carry))[0])
                syncs += 1
            history.append(rec)
        history.append(dict(
            timing=True, fused=False, blocking_syncs=syncs,
            wall_s=round(time.monotonic() - t_start, 6),
        ))
        return self.final_state(carry), history

    def objective(self, carry) -> float:
        return float(np.asarray(self._jit_obj(self.statics, carry))[0])

    def final_state(self, carry) -> ClusterState:
        """Reassemble the optimized placement in the original replica order."""
        lay = self.layout
        rb = np.asarray(carry.replica_broker)  # [n, R_local]
        rl = np.asarray(carry.replica_is_leader)
        rd = np.asarray(carry.replica_disk)
        st = self.global_state
        g_rb = np.array(np.asarray(st.replica_broker))
        g_rl = np.array(np.asarray(st.replica_is_leader))
        g_rd = np.array(np.asarray(st.replica_disk))
        own = lay.orig_index >= 0
        idx = lay.orig_index[own]
        g_rb[idx] = rb[own]
        g_rl[idx] = rl[own]
        g_rd[idx] = rd[own]
        alive = np.asarray(st.broker_alive)
        dalive = np.asarray(st.disk_alive)
        offline = ~(alive[g_rb] & dalive[g_rb, g_rd]) & np.asarray(st.replica_valid)
        return dataclasses.replace(
            st,
            replica_broker=jnp.asarray(g_rb),
            replica_is_leader=jnp.asarray(g_rl),
            replica_disk=jnp.asarray(g_rd),
            replica_offline=jnp.asarray(offline),
        )
