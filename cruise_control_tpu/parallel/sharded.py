"""Candidate-sharded optimization: one chain, K candidates split over devices.

``ShardedEngine`` is the 1-chain view of the shared mesh engine layer
(parallel/mesh.py): ``Mesh((restart=1, model=n))``.  Each step the full-K
candidate stream is drawn from the replicated key, each device evaluates
objective deltas for its K/n slice, and one tiled ``all_gather`` of the
candidate COLUMNS reassembles the full-K bundle for the global conflict
resolution that runs identically everywhere.  The model and carry are
replicated, so a 1-device and an n-device run of the same seeded anneal
produce byte-identical placements (mesh.py module docstring).

This file is deliberately thin: every jit/shard_map/collective lives in
parallel/mesh.py, shared verbatim with grid.py and portfolio.py.  The
pre-round-6 replica/partition-axis sharding implementation that used to
live here (per-shard RNG streams, psum'd aggregate refresh) was replaced —
it made 1-vs-N parity impossible and ran ~22% slower than the plain engine
at n=1 (VERDICT r5 item 4).  Replica/partition-axis sharding now exists as
the mesh engine's sharded-MODEL mode (parallel/model_shard.py +
``MeshEngine(model_shard_min_partitions=...)``), which keeps every RNG
draw replicated and resolves row gathers by ownership psums — parity
preserved, per-chip model memory ~1/n.

Reference analog: none — the reference optimizer is a single-threaded Java
loop (analyzer/goals/AbstractGoal.java:66-107).
"""

from __future__ import annotations

from cruise_control_tpu.parallel.mesh import (
    MODEL_AXIS,
    MeshEngine,
    model_mesh,
    shard_map_compat,
)

__all__ = ["MODEL_AXIS", "ShardedEngine", "model_mesh", "shard_map_compat"]


class ShardedEngine(MeshEngine):
    """One annealing chain whose candidate axis is sharded over the mesh.

    Constructor contract (state, chain, mesh, constraint, options, config,
    bucket) is inherited unchanged from MeshEngine; a 1D ``(model,)`` mesh
    (``model_mesh()``) is normalized to the canonical 2D ``(restart=1,
    model=n)`` layout.  ``run()`` executes the plain engine's fused
    multi-round schedule; at n=1 the traced program IS the plain fused
    program (no collective is emitted)."""
