"""Multi-device parallelism: restart portfolios (DP) + model sharding.

Two orthogonal axes over a `jax.sharding.Mesh` (SURVEY §2.6):
  * portfolio.py — independent SA chains sharded over devices, winner by
    all_gather race (data parallelism over restarts);
  * sharded.py — the cluster model itself sharded (replica/partition axes)
    with replicated broker aggregates and psum'd refresh, for models
    exceeding one chip's HBM ("replica-axis sharding is our sequence
    parallelism").
"""

from cruise_control_tpu.parallel.grid import GridEngine, grid_mesh
from cruise_control_tpu.parallel.portfolio import default_mesh, portfolio_run
from cruise_control_tpu.parallel.sharded import (
    MODEL_AXIS,
    ShardedEngine,
    build_layout,
    model_mesh,
)

__all__ = [
    "GridEngine",
    "MODEL_AXIS",
    "ShardedEngine",
    "build_layout",
    "default_mesh",
    "grid_mesh",
    "model_mesh",
    "portfolio_run",
]
