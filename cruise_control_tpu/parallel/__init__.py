"""Multi-device parallelism: ONE mesh-native engine layer (mesh.py).

Every multi-device mode is a view of the same shard_map'd program over an
explicit 2D ``Mesh((restart, model))`` (see mesh.py module docstring):

  * sharded.py  — Mesh(1, n): one chain, candidate axis sharded n ways;
  * portfolio.py — Mesh(n, 1): independent SA chains racing to the best
    objective (data parallelism over restarts);
  * grid.py     — Mesh(R, M): a portfolio OF candidate-sharded chains.

The jit/shard_map/collective plumbing lives ONLY in mesh.py; the three
mode modules are thin, named views of it.
"""

from cruise_control_tpu.parallel.grid import GridEngine
from cruise_control_tpu.parallel.mesh import (
    MODEL_AXIS,
    RESTART_AXIS,
    MeshEngine,
    default_mesh,
    grid_mesh,
    model_mesh,
    normalize_mesh,
    shard_map_compat,
)
from cruise_control_tpu.parallel.model_shard import ShardPlan
from cruise_control_tpu.parallel.portfolio import portfolio_run
from cruise_control_tpu.parallel.sharded import ShardedEngine

__all__ = [
    "GridEngine",
    "MODEL_AXIS",
    "MeshEngine",
    "RESTART_AXIS",
    "ShardPlan",
    "ShardedEngine",
    "default_mesh",
    "grid_mesh",
    "model_mesh",
    "normalize_mesh",
    "portfolio_run",
    "shard_map_compat",
]
