"""Greedy CPU oracle — a faithful re-expression of the reference search.

Mirrors reference analyzer/goals/AbstractGoal.optimize:66-107: goals are
optimized strictly in priority order; for each goal, brokers are visited
and single replica/leadership moves are applied when they (a) help the
current goal and (b) do not regress any previously-optimized goal
(reference AnalyzerUtils.isProposalAcceptableForOptimizedGoals:119).

This exists for TESTS AND BENCHMARKS ONLY: it is the quality baseline the
batched TPU engine must match or beat (SURVEY §7 "equal-or-better on the
aggregate score"), the role OptimizationVerifier's greedy runs play in the
reference test suite.  numpy, single-threaded, deliberately simple.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import ClusterState


def _violations(state: ClusterState, chain: GoalChain, constraint) -> np.ndarray:
    agg = compute_aggregates(state)
    return np.asarray(
        [float(g.violation(state, agg, constraint)) for g in chain.goals], np.float64
    )


def greedy_optimize(
    state: ClusterState,
    chain: GoalChain,
    constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
    *,
    max_moves_per_goal: int = 200,
    candidate_dests: int = 10,
    seed: int = 0,
) -> ClusterState:
    """Sequential greedy search over single moves, reference-style.

    For tractability the oracle samples `candidate_dests` destinations per
    source replica instead of scanning all brokers (the reference prunes
    similarly via sorted candidate lists, model/SortedReplicas.java:47).
    """
    rng = np.random.default_rng(seed)
    cur = state
    viol = _violations(cur, chain, constraint)

    for gi in range(len(chain.goals)):
        for _ in range(max_moves_per_goal):
            if viol[gi] <= 1e-12:
                break
            improved = False
            move = _find_improving_move(
                cur, chain, constraint, viol, gi, rng, candidate_dests
            )
            if move is not None:
                cur, viol = move
                improved = True
            if not improved:
                break
    return cur


def _find_improving_move(cur, chain, constraint, viol, gi, rng, candidate_dests):
    """One accepted move: improves goal gi without regressing goals < gi."""
    valid = np.asarray(cur.replica_valid)
    brokers = np.asarray(cur.replica_broker)
    alive = np.asarray(cur.broker_alive) & np.asarray(cur.broker_valid)
    alive_ids = np.nonzero(alive)[0]
    part = np.asarray(cur.replica_partition)

    # candidate source replicas: prefer replicas on dead or overloaded brokers
    ridx = np.nonzero(valid)[0]
    rng.shuffle(ridx)
    for r in ridx[:64]:
        src = brokers[r]
        dests = rng.choice(alive_ids, size=min(candidate_dests, alive_ids.size), replace=False)
        for dst in dests:
            if dst == src:
                continue
            # no duplicate replica of the partition on dst
            if ((part == part[r]) & (brokers == dst) & valid).any():
                continue
            nxt = _apply_move(cur, int(r), int(dst))
            nviol = _violations(nxt, chain, constraint)
            if nviol[gi] < viol[gi] - 1e-12 and not (
                nviol[:gi] > viol[:gi] + 1e-9
            ).any():
                return nxt, nviol
    return None


def _apply_move(cur: ClusterState, r: int, dst: int) -> ClusterState:
    import jax.numpy as jnp

    rb = np.asarray(cur.replica_broker).copy()
    rb[r] = dst
    offline = np.asarray(cur.replica_offline).copy()
    offline[r] = not bool(np.asarray(cur.broker_alive)[dst])
    return dataclasses.replace(
        cur,
        replica_broker=jnp.asarray(rb),
        replica_offline=jnp.asarray(offline),
    )
