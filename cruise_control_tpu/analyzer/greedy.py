"""Greedy CPU oracle — a faithful re-expression of the reference search.

Mirrors reference analyzer/goals/AbstractGoal.optimize:66-107: goals are
optimized strictly in priority order; for each goal, brokers are visited
and moves are applied when they (a) help the current goal and (b) do not
regress any previously-optimized goal (reference
AnalyzerUtils.isProposalAcceptableForOptimizedGoals:119).  The move
neighborhood matches the reference's: single replica relocations
(AbstractGoal.maybeApplyBalancingAction:179), leadership transfers
(ActionType.LEADERSHIP_MOVEMENT; LeaderBytesInDistributionGoal), and
replica swaps (AbstractGoal.maybeApplySwapAction:236,
ResourceDistributionGoal.java:502-599).

This exists for TESTS AND BENCHMARKS ONLY: it is the quality baseline the
batched TPU engine must match or beat (SURVEY §7 "equal-or-better on the
aggregate score"), the role OptimizationVerifier's greedy runs play in the
reference test suite.  Single-threaded; candidate evaluation goes through
one jitted violation function so large fixtures stay tractable, and a
wall-clock budget caps total work the way the reference's minutes-long
runs would be capped in practice.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from cruise_control_tpu.analyzer.objective import GoalChain
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import ClusterState


def _make_eval(chain: GoalChain, constraint):
    """One jitted program evaluating all goal violations for a state."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def eval_fn(s: ClusterState):
        agg = compute_aggregates(s)
        return jnp.stack([g.violation(s, agg, constraint) for g in chain.goals])

    return lambda s: np.asarray(eval_fn(s), np.float64)


def greedy_optimize(
    state: ClusterState,
    chain: GoalChain,
    constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
    *,
    max_moves_per_goal: int = 200,
    candidate_dests: int = 10,
    seed: int = 0,
    time_budget_s: float | None = None,
    return_info: bool = False,
    device=None,
    options=None,
):
    """Sequential greedy search over single moves, reference-style.

    For tractability the oracle samples `candidate_dests` destinations per
    source replica instead of scanning all brokers (the reference prunes
    similarly via sorted candidate lists, model/SortedReplicas.java:47).
    `time_budget_s` bounds wall-clock: when exhausted, the best state so
    far is returned (the reference search at LinkedIn scale runs minutes;
    benchmarks cap it to keep rounds bounded).

    With `return_info` returns (state, info) where info records whether the
    run CONVERGED (terminated on its own: goals satisfied or no improving
    move within the sampled neighborhood) vs hit the deadline — baseline
    generation needs the distinction (a truncated oracle understates the
    bar, VERDICT r2 weak #4).

    `device` pins the whole search — the jitted evaluation AND the
    candidate states the move applicators build — to a specific backend
    device: the service's DEGRADED mode runs the oracle with device=cpu
    while the accelerator is circuit-broken, so the fallback cannot hang
    on the very device it is falling back from.

    `options` (analyzer.options.OptimizationOptions) applies the same
    movement restrictions the engine honors: excluded topics stay put
    (unless offline), excluded/requested destination masks bound where
    replicas may land, and leadership never moves onto
    excluded-for-leadership brokers — so a DEGRADED self-healing fix keeps
    its exclusion contract (recently removed/demoted brokers).
    """
    import contextlib

    import jax

    ctx = (
        jax.default_device(device) if device is not None else contextlib.nullcontext()
    )
    with ctx:
        return _greedy_optimize_impl(
            state, chain, constraint,
            max_moves_per_goal=max_moves_per_goal,
            candidate_dests=candidate_dests,
            seed=seed,
            time_budget_s=time_budget_s,
            return_info=return_info,
            restrictions=_MoveRestrictions.from_options(state, options),
        )


@dataclasses.dataclass(frozen=True)
class _MoveRestrictions:
    """OptimizationOptions rendered as plain numpy masks for the oracle.

    Built through the options' own mask helpers so the oracle shares the
    engine's fitting semantics exactly — notably, a stale mask shorter
    than the real broker count FAILS LOUDLY instead of silently
    un-excluding brokers (OptimizationOptions._fit)."""

    dest_allowed: np.ndarray  # bool[B], replica-move destinations
    lead_allowed: np.ndarray  # bool[B], may receive leadership
    topic_movable: np.ndarray  # bool[T], False = stays put unless offline

    @staticmethod
    def from_options(state: ClusterState, options) -> "_MoveRestrictions":
        from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS

        options = options if options is not None else DEFAULT_OPTIONS
        return _MoveRestrictions(
            dest_allowed=options.dest_allowed(state),
            lead_allowed=options.leadership_allowed(state),
            topic_movable=options.topic_movable(state),
        )


def _greedy_optimize_impl(
    state: ClusterState,
    chain: GoalChain,
    constraint: BalancingConstraint,
    *,
    max_moves_per_goal: int,
    candidate_dests: int,
    seed: int,
    time_budget_s: float | None,
    return_info: bool,
    restrictions: "_MoveRestrictions",
):
    rng = np.random.default_rng(seed)
    eval_fn = _make_eval(chain, constraint)
    cur = state
    viol = eval_fn(cur)
    t0 = time.monotonic()
    deadline = t0 + time_budget_s if time_budget_s else None
    moves = 0
    hit_deadline = False
    hit_move_cap = False

    for gi in range(len(chain.goals)):
        if hit_deadline:
            break
        moves_this_goal = 0
        while True:
            if viol[gi] <= 1e-12:
                break
            if moves_this_goal >= max_moves_per_goal:
                # ran out of per-goal move budget with the goal still
                # violated — truncation, NOT convergence
                hit_move_cap = True
                break
            if deadline is not None and time.monotonic() > deadline:
                hit_deadline = True
                break
            move = _find_improving_move(
                cur, eval_fn, viol, gi, rng, candidate_dests, deadline, restrictions
            )
            if move is None:
                # a deadline that fired inside the move search is truncation,
                # not convergence
                if deadline is not None and time.monotonic() > deadline:
                    hit_deadline = True
                break
            cur, viol = move
            moves += 1
            moves_this_goal += 1
    if return_info:
        return cur, dict(
            converged=not hit_deadline and not hit_move_cap,
            moves=moves,
            seconds=round(time.monotonic() - t0, 1),
        )
    return cur


def _find_improving_move(
    cur, eval_fn, viol, gi, rng, candidate_dests, deadline, restrictions
):
    """One accepted move: improves goal gi without regressing goals < gi.

    Tries, in the reference's order, relocation -> leadership transfer ->
    swap for each sampled source replica.  `restrictions` bounds the
    neighborhood: destination masks apply to relocations and both sides of
    a swap, excluded topics only move while offline, and leadership never
    lands on an excluded-for-leadership broker.
    """
    valid = np.asarray(cur.replica_valid)
    brokers = np.asarray(cur.replica_broker)
    is_leader = np.asarray(cur.replica_is_leader)
    offline = np.asarray(cur.replica_offline)
    topic = np.asarray(cur.replica_topic)
    alive = np.asarray(cur.broker_alive) & np.asarray(cur.broker_valid)
    alive_ids = np.nonzero(alive & restrictions.dest_allowed)[0]
    part = np.asarray(cur.replica_partition)

    def accepted(nxt):
        nviol = eval_fn(nxt)
        if nviol[gi] < viol[gi] - 1e-12 and not (nviol[:gi] > viol[:gi] + 1e-9).any():
            return nxt, nviol
        return None

    ridx = np.nonzero(valid)[0]
    rng.shuffle(ridx)
    for r in ridx[:64]:
        if deadline is not None and time.monotonic() > deadline:
            return None
        src = brokers[r]
        # excluded-topic replicas stay put unless offline (reference
        # excludedTopics semantics); leadership transfers stay allowed
        movable = restrictions.topic_movable[topic[r]] or offline[r]
        dests = rng.choice(
            alive_ids, size=min(candidate_dests, alive_ids.size), replace=False
        )

        # 1. relocation (reference maybeApplyBalancingAction)
        if movable:
            for dst in dests:
                if deadline is not None and time.monotonic() > deadline:
                    return None
                if dst == src:
                    continue
                # a relocating LEADER replica carries leadership along
                if is_leader[r] and not restrictions.lead_allowed[dst]:
                    continue
                if ((part == part[r]) & (brokers == dst) & valid).any():
                    continue
                got = accepted(_apply_move(cur, int(r), int(dst)))
                if got is not None:
                    return got

        # 2. leadership transfer (reference ActionType.LEADERSHIP_MOVEMENT)
        if not is_leader[r] and alive[src] and restrictions.lead_allowed[src]:
            leader_mask = (part == part[r]) & is_leader & valid
            if leader_mask.any():
                got = accepted(_apply_leadership(cur, int(r), int(leader_mask.argmax())))
                if got is not None:
                    return got

        # 3. swap with a replica on a destination broker (reference
        #    maybeApplySwapAction:236, ResourceDistributionGoal swap-in/out)
        # the counterpart lands on src, so src must be an allowed
        # destination too
        if movable and restrictions.dest_allowed[src]:
            for dst in dests:
                if deadline is not None and time.monotonic() > deadline:
                    return None
                if dst == src:
                    continue
                on_dst = np.nonzero(valid & (brokers == dst) & (part != part[r]))[0]
                if on_dst.size == 0:
                    continue
                q = int(on_dst[rng.integers(on_dst.size)])
                # the counterpart replica is bound by the same topic rule
                if not restrictions.topic_movable[topic[q]] and not offline[q]:
                    continue
                # leadership travels with a swapped leader replica too
                if is_leader[r] and not restrictions.lead_allowed[dst]:
                    continue
                if is_leader[q] and not restrictions.lead_allowed[src]:
                    continue
                # neither partition may end up duplicated
                if ((part == part[r]) & (brokers == dst) & valid).any():
                    continue
                if ((part == part[q]) & (brokers == src) & valid).any():
                    continue
                got = accepted(_apply_swap(cur, int(r), int(q)))
                if got is not None:
                    return got
    return None


def _apply_move(cur: ClusterState, r: int, dst: int) -> ClusterState:
    import jax.numpy as jnp

    rb = np.asarray(cur.replica_broker).copy()
    rb[r] = dst
    offline = np.asarray(cur.replica_offline).copy()
    offline[r] = not bool(np.asarray(cur.broker_alive)[dst])
    return dataclasses.replace(
        cur,
        replica_broker=jnp.asarray(rb),
        replica_offline=jnp.asarray(offline),
    )


def _apply_leadership(cur: ClusterState, rt: int, rf: int) -> ClusterState:
    """Transfer leadership of a partition from replica rf to replica rt."""
    import jax.numpy as jnp

    lead = np.asarray(cur.replica_is_leader).copy()
    lead[rf] = False
    lead[rt] = True
    return dataclasses.replace(cur, replica_is_leader=jnp.asarray(lead))


def _apply_swap(cur: ClusterState, r: int, q: int) -> ClusterState:
    """Swap the brokers of replicas r and q (different partitions)."""
    import jax.numpy as jnp

    rb = np.asarray(cur.replica_broker).copy()
    rb[r], rb[q] = rb[q], rb[r]
    alive = np.asarray(cur.broker_alive)
    offline = np.asarray(cur.replica_offline).copy()
    offline[r] = not bool(alive[rb[r]])
    offline[q] = not bool(alive[rb[q]])
    return dataclasses.replace(
        cur,
        replica_broker=jnp.asarray(rb),
        replica_offline=jnp.asarray(offline),
    )
