"""Optimization options — exclusion masks and destination restriction.

Reference: analyzer/OptimizationOptions.java (excluded topics, brokers
excluded for leadership / replica moves, requested destination brokers).
Here every exclusion is a dense mask over the topic/broker axis so the
engine can apply them as vectorized feasibility predicates.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.models.state import ClusterState


@dataclasses.dataclass(frozen=True)
class OptimizationOptions:
    #: replicas of these topics stay put unless offline (reference
    #: OptimizationOptions.excludedTopics)
    excluded_topics: np.ndarray | None = None  # bool[T]
    #: brokers that may not *receive* leadership (reference
    #: excludedBrokersForLeadership)
    excluded_brokers_for_leadership: np.ndarray | None = None  # bool[B]
    #: brokers that may not *receive* replicas (reference
    #: excludedBrokersForReplicaMove)
    excluded_brokers_for_replica_move: np.ndarray | None = None  # bool[B]
    #: if set, replica moves may only land on these brokers (reference
    #: requestedDestinationBrokerIds; used by add_broker/rebalance-to)
    requested_destination_brokers: np.ndarray | None = None  # bool[B]

    def __post_init__(self):
        # normalize every mask to a 1-D bool ndarray at construction — a
        # wrong-rank or non-boolean mask otherwise broadcasts or fails deep
        # inside the jitted engine with an inscrutable shape error
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            arr = np.asarray(v, bool)
            if arr.ndim != 1:
                raise ValueError(
                    f"{f.name} must be a 1-D boolean mask, got shape {arr.shape}"
                )
            object.__setattr__(self, f.name, arr)

    def dest_allowed(self, state: ClusterState) -> np.ndarray:
        B = state.shape.B
        allowed = np.ones(B, bool)
        if self.excluded_brokers_for_replica_move is not None:
            allowed &= ~np.asarray(self.excluded_brokers_for_replica_move, bool)
        if self.requested_destination_brokers is not None:
            allowed &= np.asarray(self.requested_destination_brokers, bool)
        return allowed

    def leadership_allowed(self, state: ClusterState) -> np.ndarray:
        B = state.shape.B
        allowed = np.ones(B, bool)
        if self.excluded_brokers_for_leadership is not None:
            allowed &= ~np.asarray(self.excluded_brokers_for_leadership, bool)
        return allowed

    def topic_movable(self, state: ClusterState) -> np.ndarray:
        T = state.shape.num_topics
        movable = np.ones(T, bool)
        if self.excluded_topics is not None:
            movable &= ~np.asarray(self.excluded_topics, bool)
        return movable


DEFAULT_OPTIONS = OptimizationOptions()
