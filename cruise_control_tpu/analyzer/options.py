"""Optimization options — exclusion masks and destination restriction.

Reference: analyzer/OptimizationOptions.java (excluded topics, brokers
excluded for leadership / replica moves, requested destination brokers).
Here every exclusion is a dense mask over the topic/broker axis so the
engine can apply them as vectorized feasibility predicates.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.models.state import ClusterState


@dataclasses.dataclass(frozen=True)
class OptimizationOptions:
    #: replicas of these topics stay put unless offline (reference
    #: OptimizationOptions.excludedTopics)
    excluded_topics: np.ndarray | None = None  # bool[T]
    #: brokers that may not *receive* leadership (reference
    #: excludedBrokersForLeadership)
    excluded_brokers_for_leadership: np.ndarray | None = None  # bool[B]
    #: brokers that may not *receive* replicas (reference
    #: excludedBrokersForReplicaMove)
    excluded_brokers_for_replica_move: np.ndarray | None = None  # bool[B]
    #: if set, replica moves may only land on these brokers (reference
    #: requestedDestinationBrokerIds; used by add_broker/rebalance-to)
    requested_destination_brokers: np.ndarray | None = None  # bool[B]

    def __post_init__(self):
        # normalize every mask to a 1-D bool ndarray at construction — a
        # wrong-rank or non-boolean mask otherwise broadcasts or fails deep
        # inside the jitted engine with an inscrutable shape error
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            arr = np.asarray(v, bool)
            if arr.ndim != 1:
                raise ValueError(
                    f"{f.name} must be a 1-D boolean mask, got shape {arr.shape}"
                )
            object.__setattr__(self, f.name, arr)

    @staticmethod
    def _fit(mask: np.ndarray, n: int, name: str, *, n_real: int = 0) -> np.ndarray:
        """Fit a mask built against REAL entity counts to a (possibly
        shape-bucketed) padded axis: padding rows are never excluded /
        requested, so [n_real, n) extends with False.  A mask SHORTER than
        the real entity count is a stale/wrong-cluster mask (e.g. built
        before a broker add) and fails loudly — silently un-excluding the
        uncovered entities would defeat the operator's intent."""
        mask = np.asarray(mask, bool)
        if mask.size > n:
            raise ValueError(f"{name} mask has {mask.size} entries for axis {n}")
        if mask.size < n_real:
            raise ValueError(
                f"{name} mask covers {mask.size} of {n_real} real entities"
            )
        if mask.size < n:
            mask = np.pad(mask, (0, n - mask.size))
        return mask

    def dest_allowed(self, state: ClusterState) -> np.ndarray:
        B = state.shape.B
        n_real = int(np.asarray(state.broker_valid).sum())
        allowed = np.ones(B, bool)
        if self.excluded_brokers_for_replica_move is not None:
            allowed &= ~self._fit(
                self.excluded_brokers_for_replica_move, B,
                "excluded_brokers_for_replica_move", n_real=n_real,
            )
        if self.requested_destination_brokers is not None:
            allowed &= self._fit(
                self.requested_destination_brokers, B,
                "requested_destination_brokers", n_real=n_real,
            )
        return allowed

    def leadership_allowed(self, state: ClusterState) -> np.ndarray:
        B = state.shape.B
        allowed = np.ones(B, bool)
        if self.excluded_brokers_for_leadership is not None:
            allowed &= ~self._fit(
                self.excluded_brokers_for_leadership, B,
                "excluded_brokers_for_leadership",
                n_real=int(np.asarray(state.broker_valid).sum()),
            )
        return allowed

    def topic_movable(self, state: ClusterState) -> np.ndarray:
        # no real-count floor here: the state carries no topic-validity
        # axis to check against, and the service path rebuilds
        # excluded_topics from the CURRENT catalog on every request
        # (facade._build_options) — a short mask can only mean topics
        # created since the mask was built, which stay movable exactly as
        # the reference's evaluate-the-regex-at-request-time semantics
        # would leave them.
        T = state.shape.num_topics
        movable = np.ones(T, bool)
        if self.excluded_topics is not None:
            movable &= ~self._fit(self.excluded_topics, T, "excluded_topics")
        return movable


DEFAULT_OPTIONS = OptimizationOptions()
