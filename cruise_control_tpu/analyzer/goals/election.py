"""PreferredLeaderElectionGoal (reference analyzer/goals/PreferredLeaderElectionGoal.java).

A utility goal: leadership should sit on the first (preferred, pos == 0)
replica of each partition whenever that replica is on a healthy broker.
Violation = fraction of partitions led by a non-preferred replica while the
preferred one is eligible.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.common.collectives import gsum
from cruise_control_tpu.models.aggregates import BrokerAggregates
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.analyzer.goals.base import Goal


class PreferredLeaderElectionGoal(Goal):
    name = "PreferredLeaderElectionGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        preferred = state.replica_pos == 0
        eligible = state.broker_alive[state.replica_broker] & ~state.replica_offline
        # partition is violated if its preferred replica is eligible but not leader
        bad = state.replica_valid & preferred & eligible & ~state.replica_is_leader
        P = jnp.maximum(state.shape.P, 1)  # global padded P (shape is metadata)
        return gsum(bad).astype(jnp.float32) / P
