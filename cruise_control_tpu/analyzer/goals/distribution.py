"""Soft distribution goals.

Reference: analyzer/goals/ResourceDistributionGoal.java:50 (+4 subclasses),
ReplicaDistributionGoal.java, LeaderReplicaDistributionGoal.java,
LeaderBytesInDistributionGoal.java, TopicReplicaDistributionGoal.java.

Balance semantics follow the reference: the per-broker target band is
capacity-proportional for resources (avg utilization percentage x balance
threshold x broker capacity) and count-proportional for replica counts
(cluster average +/- threshold).  `score` adds the coefficient of variation
as a continuous tiebreaker so optimization keeps tightening balance inside
the band.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.aggregates import BrokerAggregates
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.analyzer.goals.base import Goal, alive_mask, relu


def _band_violation(values, mask, upper, lower, scale):
    """Sum of band excursions over masked entries, normalized by scale."""
    over = relu(jnp.where(mask, values - upper, 0.0))
    under = relu(jnp.where(mask, lower - values, 0.0))
    return (over + under).sum() / (scale + 1e-12)


def _cv(values, mask):
    """Coefficient of variation over masked entries."""
    n = jnp.maximum(mask.sum(), 1)
    mean = jnp.where(mask, values, 0.0).sum() / n
    var = jnp.where(mask, (values - mean) ** 2, 0.0).sum() / n
    return jnp.sqrt(var) / (mean + 1e-12)


class ResourceDistributionGoal(Goal):
    """Per-broker utilization within avg% * (2-t, t) * capacity for one resource."""

    hard = False

    def __init__(self, resource: Resource):
        self.resource = resource
        self.name = {
            Resource.CPU: "CpuUsageDistributionGoal",
            Resource.NW_IN: "NetworkInboundUsageDistributionGoal",
            Resource.NW_OUT: "NetworkOutboundUsageDistributionGoal",
            Resource.DISK: "DiskUsageDistributionGoal",
        }[resource]

    def _bands(self, state, agg, constraint):
        r = int(self.resource)
        t = constraint.balance_threshold[r]
        mask = alive_mask(state)
        cap = jnp.where(mask, state.broker_capacity[:, r], 0.0)
        load = jnp.where(mask, agg.broker_load[:, r], 0.0)
        avg_pct = load.sum() / (cap.sum() + 1e-12)
        upper = avg_pct * t * cap
        lower = avg_pct * max(0.0, 2.0 - t) * cap
        return load, mask, upper, lower

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        load, mask, upper, lower = self._bands(state, agg, constraint)
        return _band_violation(load, mask, upper, lower, load.sum())

    def score(self, state: ClusterState, agg: BrokerAggregates, constraint):
        r = int(self.resource)
        mask = alive_mask(state)
        # dispersion of utilization *percentage* so heterogeneous capacities
        # aren't penalized
        pct = agg.broker_load[:, r] / (state.broker_capacity[:, r] + 1e-12)
        return _cv(jnp.where(mask, pct, 0.0), mask)


class _CountDistributionGoal(Goal):
    """Shared count-balance logic for replica/leader count goals."""

    def _counts(self, state: ClusterState, agg: BrokerAggregates):
        raise NotImplementedError

    def _threshold(self, constraint) -> float:
        raise NotImplementedError

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        mask = alive_mask(state)
        counts = jnp.where(mask, self._counts(state, agg), 0).astype(jnp.float32)
        n = jnp.maximum(mask.sum(), 1)
        avg = counts.sum() / n
        t = self._threshold(constraint)
        # reference uses ceil/floor of avg*t (ReplicaDistributionAbstractGoal)
        upper = jnp.ceil(avg * t)
        lower = jnp.floor(avg * max(0.0, 2.0 - t))
        return _band_violation(counts, mask, upper, lower, counts.sum())

    def score(self, state: ClusterState, agg: BrokerAggregates, constraint):
        mask = alive_mask(state)
        return _cv(jnp.where(mask, self._counts(state, agg), 0).astype(jnp.float32), mask)


class ReplicaDistributionGoal(_CountDistributionGoal):
    name = "ReplicaDistributionGoal"

    def _counts(self, state, agg):
        return agg.broker_replica_count

    def _threshold(self, constraint):
        return constraint.replica_count_balance_threshold


class LeaderReplicaDistributionGoal(_CountDistributionGoal):
    name = "LeaderReplicaDistributionGoal"

    def _counts(self, state, agg):
        return agg.broker_leader_count

    def _threshold(self, constraint):
        return constraint.leader_replica_count_balance_threshold


class LeaderBytesInDistributionGoal(Goal):
    """Leader-served NW_IN balanced across brokers
    (reference analyzer/goals/LeaderBytesInDistributionGoal.java)."""

    name = "LeaderBytesInDistributionGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        t = constraint.balance_threshold[int(Resource.NW_IN)]
        mask = alive_mask(state)
        lbin = jnp.where(mask, agg.broker_leader_bytes_in, 0.0)
        n = jnp.maximum(mask.sum(), 1)
        avg = lbin.sum() / n
        # reference only caps the upper side (moves leadership off hot brokers)
        return _band_violation(lbin, mask, avg * t, 0.0, lbin.sum())

    def score(self, state: ClusterState, agg: BrokerAggregates, constraint):
        mask = alive_mask(state)
        return _cv(jnp.where(mask, agg.broker_leader_bytes_in, 0.0), mask)


class TopicReplicaDistributionGoal(Goal):
    """Per-topic replica spread balanced across brokers
    (reference analyzer/goals/TopicReplicaDistributionGoal.java)."""

    name = "TopicReplicaDistributionGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        mask = alive_mask(state)  # [B]
        counts = jnp.where(mask[None, :], agg.broker_topic_count, 0).astype(jnp.float32)
        n = jnp.maximum(mask.sum(), 1)
        avg = counts.sum(axis=1, keepdims=True) / n  # [T, 1]
        t = constraint.topic_replica_count_balance_threshold
        upper = jnp.ceil(avg * t)
        lower = jnp.floor(avg * max(0.0, 2.0 - t))
        return _band_violation(counts, mask[None, :], upper, lower, counts.sum())
