"""Hard capacity goals.

Reference: analyzer/goals/CapacityGoal.java:42 and its four thin subclasses
(DiskCapacityGoal, NetworkInbound/OutboundCapacityGoal, CpuCapacityGoal),
ReplicaCapacityGoal.java, PotentialNwOutGoal.java.

Violations are dimensionless: excess utilization divided by total alive
capacity for that resource, so resources and goals are comparable inside one
scalar objective.  Host-level checking mirrors the reference: host resources
(CPU, NW) are checked at host granularity when a host has >1 broker,
broker granularity otherwise (reference CapacityGoal host/broker split).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.collectives import gsum
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.aggregates import BrokerAggregates, host_load
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.analyzer.goals.base import Goal, alive_mask, relu


class CapacityGoal(Goal):
    """Broker/host utilization below capacity * capacity_threshold for one resource."""

    hard = True

    def __init__(self, resource: Resource):
        self.resource = resource
        self.name = {
            Resource.CPU: "CpuCapacityGoal",
            Resource.NW_IN: "NetworkInboundCapacityGoal",
            Resource.NW_OUT: "NetworkOutboundCapacityGoal",
            Resource.DISK: "DiskCapacityGoal",
        }[resource]

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        r = int(self.resource)
        thresh = constraint.capacity_threshold[r]
        mask = alive_mask(state)
        cap = jnp.where(mask, state.broker_capacity[:, r], 0.0)
        load = jnp.where(mask, agg.broker_load[:, r], 0.0)
        scale = cap.sum() + 1e-12

        broker_excess = relu(load - thresh * cap)
        if self.resource.is_host_resource:
            H = state.shape.num_hosts
            hseg = jnp.where(state.broker_valid, state.broker_host, H)
            brokers_per_host = jax.ops.segment_sum(
                mask.astype(jnp.int32), hseg, num_segments=H + 1
            )[:H]
            h_load = jax.ops.segment_sum(load, hseg, num_segments=H + 1)[:H]
            h_cap = jax.ops.segment_sum(cap, hseg, num_segments=H + 1)[:H]
            host_excess = relu(h_load - thresh * h_cap)
            multi = brokers_per_host > 1
            # host granularity where hosts aggregate several brokers,
            # broker granularity otherwise (single-broker hosts coincide).
            host_term = jnp.where(multi, host_excess, 0.0).sum()
            per_host_single = jax.ops.segment_sum(
                broker_excess, hseg, num_segments=H + 1
            )[:H]
            broker_term = jnp.where(~multi, per_host_single, 0.0).sum()
            return (host_term + broker_term) / scale
        return broker_excess.sum() / scale


class ReplicaCapacityGoal(Goal):
    """<= max.replicas.per.broker on every alive broker
    (reference analyzer/goals/ReplicaCapacityGoal.java)."""

    name = "ReplicaCapacityGoal"
    hard = True

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        mask = alive_mask(state)
        count = jnp.where(mask, agg.broker_replica_count, 0)
        excess = relu((count - constraint.max_replicas_per_broker).astype(jnp.float32))
        # replica_valid is replica-axis (model-shardable); excess is broker-axis.
        n_valid = gsum(state.replica_valid).astype(jnp.float32) + 1e-12
        return excess.sum() / n_valid


class PotentialNwOutGoal(Goal):
    """Potential (all-leader) NW-out under capacity threshold
    (reference analyzer/goals/PotentialNwOutGoal.java)."""

    name = "PotentialNwOutGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        r = int(Resource.NW_OUT)
        thresh = constraint.capacity_threshold[r]
        mask = alive_mask(state)
        cap = jnp.where(mask, state.broker_capacity[:, r], 0.0)
        pot = jnp.where(mask, agg.broker_potential_nw_out, 0.0)
        scale = cap.sum() + 1e-12
        return relu(pot - thresh * cap).sum() / scale


class OfflineReplicaGoal(Goal):
    """No replica may remain on a dead broker or dead logdir.

    Implicit hard requirement in the reference (dead-broker replicas are
    offline and every goal's initGoalState forces their relocation; verifier
    check BROKEN_BROKERS, reference analyzer/OptimizationVerifier.java).
    Normalized by total replica count.
    """

    name = "OfflineReplicaGoal"
    hard = True

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        dead_broker = ~state.broker_alive[state.replica_broker]
        dead_disk = ~state.disk_alive[state.replica_broker, state.replica_disk]
        bad = state.replica_valid & (dead_broker | dead_disk)
        n_valid = gsum(state.replica_valid).astype(jnp.float32) + 1e-12
        return gsum(bad).astype(jnp.float32) / n_valid
