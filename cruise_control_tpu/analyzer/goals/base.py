"""Goal SPI — the TPU-native replacement for reference analyzer/goals/Goal.java:38.

The reference Goal is an imperative `optimize(clusterModel)` that mutates the
model and vetoes later goals' moves (actionAcceptance).  Here a goal is two
pure functions over array state (SURVEY §7):

  violation(state, agg, constraint) -> f32 scalar
      Total amount by which the goal is violated; 0.0 means satisfied.
      For hard goals this is a feasibility constraint the optimizer must
      drive to (and keep at) zero; for soft goals it is the primary
      objective term.

  score(state, agg, constraint) -> f32 scalar
      Continuous badness (e.g. utilization dispersion) minimized as a
      tiebreaker once violations are gone, so optimization keeps improving
      balance beyond the thresholds.

Both must be jit/vmap-compatible.  Goals are stateless and registered by the
same names the reference uses (e.g. "RackAwareGoal") so config files remain
familiar.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.config.balancing import BalancingConstraint
from cruise_control_tpu.models.aggregates import BrokerAggregates
from cruise_control_tpu.models.state import ClusterState


class Goal:
    """Base goal: zero violation, zero score."""

    #: registry name; matches the reference's class name where one exists
    name: str = "Goal"
    #: hard goals gate feasibility (reference Goal.isHardGoal)
    hard: bool = False

    def violation(
        self, state: ClusterState, agg: BrokerAggregates, constraint: BalancingConstraint
    ):
        return jnp.float32(0.0)

    def score(
        self, state: ClusterState, agg: BrokerAggregates, constraint: BalancingConstraint
    ):
        return jnp.float32(0.0)

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}(name={self.name!r}, hard={self.hard})"


def alive_mask(state: ClusterState):
    return state.broker_valid & state.broker_alive


def relu(x):
    return jnp.maximum(x, 0.0)
