"""Goal registry and default priority order.

Order mirrors the reference default.goals list
(reference config/constants/AnalyzerConfig.java:211-228); hard-goal set
mirrors AnalyzerConfig.java:246.  OfflineReplicaGoal is the implicit
dead-broker/dead-disk relocation requirement the reference bakes into every
goal's initGoalState — modeled here as an explicit top-priority hard goal.
"""

from __future__ import annotations

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.goals.capacity import (
    CapacityGoal,
    OfflineReplicaGoal,
    PotentialNwOutGoal,
    ReplicaCapacityGoal,
)
from cruise_control_tpu.analyzer.goals.distribution import (
    LeaderBytesInDistributionGoal,
    LeaderReplicaDistributionGoal,
    ReplicaDistributionGoal,
    ResourceDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.goals.election import PreferredLeaderElectionGoal
from cruise_control_tpu.analyzer.goals.topology import (
    IntraBrokerDiskCapacityGoal,
    IntraBrokerDiskUsageDistributionGoal,
    RackAwareGoal,
)
from cruise_control_tpu.analyzer.goals.kafkaassigner import (
    KafkaAssignerDiskUsageDistributionGoal,
    KafkaAssignerEvenRackAwareGoal,
)

_ALL_GOALS: list[Goal] = [
    OfflineReplicaGoal(),
    RackAwareGoal(),
    ReplicaCapacityGoal(),
    CapacityGoal(Resource.DISK),
    CapacityGoal(Resource.NW_IN),
    CapacityGoal(Resource.NW_OUT),
    CapacityGoal(Resource.CPU),
    ReplicaDistributionGoal(),
    PotentialNwOutGoal(),
    ResourceDistributionGoal(Resource.DISK),
    ResourceDistributionGoal(Resource.NW_IN),
    ResourceDistributionGoal(Resource.NW_OUT),
    ResourceDistributionGoal(Resource.CPU),
    TopicReplicaDistributionGoal(),
    LeaderReplicaDistributionGoal(),
    LeaderBytesInDistributionGoal(),
    PreferredLeaderElectionGoal(),
    IntraBrokerDiskCapacityGoal(),
    IntraBrokerDiskUsageDistributionGoal(),
    # kafka-assigner compatibility mode (reference analyzer/kafkaassigner/)
    KafkaAssignerEvenRackAwareGoal(),
    KafkaAssignerDiskUsageDistributionGoal(),
]

#: the two-goal kafka-assigner mode list (reference KafkaAssigner mode)
KAFKA_ASSIGNER_GOAL_ORDER: list[str] = [
    "KafkaAssignerEvenRackAwareGoal",
    "KafkaAssignerDiskUsageDistributionGoal",
]

GOALS_BY_NAME: dict[str, Goal] = {g.name: g for g in _ALL_GOALS}

#: default optimization order (priority high -> low), reference AnalyzerConfig.java:211-228
DEFAULT_GOAL_ORDER: list[str] = [
    "OfflineReplicaGoal",
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]

#: reference default.intra.broker.goals (AnalyzerConfig.java:236)
DEFAULT_INTRA_BROKER_GOAL_ORDER: list[str] = [
    "IntraBrokerDiskCapacityGoal",
    "IntraBrokerDiskUsageDistributionGoal",
]

HARD_GOAL_NAMES: frozenset[str] = frozenset(g.name for g in _ALL_GOALS if g.hard)


def get_goals(names: list[str] | None = None) -> list[Goal]:
    if names is None:
        names = DEFAULT_GOAL_ORDER
    unknown = [n for n in names if n not in GOALS_BY_NAME]
    if unknown:
        raise ValueError(f"unknown goals: {unknown}; known: {sorted(GOALS_BY_NAME)}")
    return [GOALS_BY_NAME[n] for n in names]


__all__ = [
    "DEFAULT_GOAL_ORDER",
    "DEFAULT_INTRA_BROKER_GOAL_ORDER",
    "GOALS_BY_NAME",
    "HARD_GOAL_NAMES",
    "Goal",
    "get_goals",
]
