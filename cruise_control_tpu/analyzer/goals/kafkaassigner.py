"""Kafka-assigner compatibility mode goals.

Reference: analyzer/kafkaassigner/KafkaAssignerEvenRackAwareGoal.java:41
(rack-aware placement that additionally spreads each replica position
evenly over brokers) and KafkaAssignerDiskUsageDistributionGoal.java:46
(swap-based disk balance).  These run as a standalone two-goal mode
(`goals=KafkaAssignerEvenRackAwareGoal,KafkaAssignerDiskUsageDistributionGoal`)
mirroring the kafka-assigner migration path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.common.collectives import gsegment_sum, gsum
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.aggregates import BrokerAggregates
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.analyzer.goals.base import Goal, alive_mask, relu


class KafkaAssignerEvenRackAwareGoal(Goal):
    """Rack awareness + even per-position replica spread.

    The reference assigns each replica position (leader, first follower, …)
    round-robin over racks; violation here combines (a) same-rack excess
    co-placement (hard part of the reference semantics) and (b) per-position
    broker-count imbalance beyond ceil(avg).
    """

    name = "KafkaAssignerEvenRackAwareGoal"
    hard = True

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        # (a) rack-awareness term, identical to RackAwareGoal
        excess = relu((agg.part_rack_count - 1).astype(jnp.float32))
        n_valid = gsum(state.replica_valid).astype(jnp.float32) + 1e-12
        out = gsum(excess) / n_valid

        # (b) per-position evenness: count replicas at position q per broker
        B = state.shape.B
        max_pos = 8  # positions above this are negligible tails
        pos = jnp.minimum(state.replica_pos, max_pos - 1)
        seg = jnp.where(
            state.replica_valid, pos * B + state.broker_segment_ids(), max_pos * B
        )
        counts = gsegment_sum(
            state.replica_valid.astype(jnp.int32), seg, num_segments=max_pos * B + 1
        )[: max_pos * B].reshape(max_pos, B)
        mask = alive_mask(state)
        counts = jnp.where(mask[None, :], counts, 0).astype(jnp.float32)
        n_alive = jnp.maximum(mask.sum(), 1)
        avg = counts.sum(axis=1, keepdims=True) / n_alive  # [max_pos, 1]
        over = relu(counts - jnp.ceil(avg))
        out += jnp.where(mask[None, :], over, 0.0).sum() / n_valid
        return out


class KafkaAssignerDiskUsageDistributionGoal(Goal):
    """Disk utilization balance, kafka-assigner flavor
    (reference analyzer/kafkaassigner/KafkaAssignerDiskUsageDistributionGoal.java:46:
    balances utilization PERCENTAGE within threshold of the mean; the
    reference reaches it via pairwise broker swaps, the SA engine reaches
    the same fixed point via its move/accept loop)."""

    name = "KafkaAssignerDiskUsageDistributionGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        r = int(Resource.DISK)
        t = constraint.balance_threshold[r]
        mask = alive_mask(state)
        pct = agg.broker_load[:, r] / (state.broker_capacity[:, r] + 1e-12)
        n = jnp.maximum(mask.sum(), 1)
        mean = jnp.where(mask, pct, 0.0).sum() / n
        dev = t - 1.0  # threshold multiplier -> absolute pct deviation band
        over = relu(jnp.where(mask, pct - (mean + dev), 0.0))
        under = relu(jnp.where(mask, (mean - dev) - pct, 0.0))
        return (over + under).sum() / jnp.maximum(mean * n, 1e-9)

    def score(self, state: ClusterState, agg: BrokerAggregates, constraint):
        r = int(Resource.DISK)
        mask = alive_mask(state)
        pct = agg.broker_load[:, r] / (state.broker_capacity[:, r] + 1e-12)
        n = jnp.maximum(mask.sum(), 1)
        mean = jnp.where(mask, pct, 0.0).sum() / n
        var = jnp.where(mask, (pct - mean) ** 2, 0.0).sum() / n
        return jnp.sqrt(var) / (mean + 1e-12)
