"""Topology goals: rack-awareness and intra-broker disk goals.

Reference: analyzer/goals/RackAwareGoal.java:43,
IntraBrokerDiskCapacityGoal.java, IntraBrokerDiskUsageDistributionGoal.java.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.common.collectives import gsum
from cruise_control_tpu.models.aggregates import BrokerAggregates
from cruise_control_tpu.models.state import ClusterState
from cruise_control_tpu.analyzer.goals.base import Goal, relu


class RackAwareGoal(Goal):
    """No two replicas of a partition on the same rack
    (reference analyzer/goals/RackAwareGoal.java:43).

    Violation counts excess same-rack co-placements:
    sum over (partition, rack) cells of max(0, count - 1), normalized by the
    replica count.  Note the reference also forgives partitions with more
    replicas than racks only by failing — we count excess the same way.
    """

    name = "RackAwareGoal"
    hard = True

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        # part_rack_count rows are model-shard-local when sharding is on.
        excess = relu((agg.part_rack_count - 1).astype(jnp.float32))
        n_valid = gsum(state.replica_valid).astype(jnp.float32) + 1e-12
        return gsum(excess) / n_valid


class IntraBrokerDiskCapacityGoal(Goal):
    """Per-logdir disk utilization under capacity threshold (JBOD)
    (reference analyzer/goals/IntraBrokerDiskCapacityGoal.java)."""

    name = "IntraBrokerDiskCapacityGoal"
    hard = True

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        from cruise_control_tpu.common.resources import Resource

        thresh = constraint.capacity_threshold[int(Resource.DISK)]
        mask = state.disk_alive & (state.broker_valid & state.broker_alive)[:, None]
        cap = jnp.where(mask, state.disk_capacity, 0.0)
        load = jnp.where(mask, agg.disk_load, 0.0)
        scale = cap.sum() + 1e-12
        # load landing on a dead logdir is itself a violation
        dead_load = jnp.where(~mask, agg.disk_load, 0.0)
        return (relu(load - thresh * cap).sum() + dead_load.sum()) / scale


class IntraBrokerDiskUsageDistributionGoal(Goal):
    """Balance utilization across a broker's logdirs
    (reference analyzer/goals/IntraBrokerDiskUsageDistributionGoal.java)."""

    name = "IntraBrokerDiskUsageDistributionGoal"
    hard = False

    def violation(self, state: ClusterState, agg: BrokerAggregates, constraint):
        from cruise_control_tpu.common.resources import Resource

        t = constraint.balance_threshold[int(Resource.DISK)]
        mask = state.disk_alive & (state.broker_valid & state.broker_alive)[:, None]
        cap = jnp.where(mask, state.disk_capacity, 0.0)
        load = jnp.where(mask, agg.disk_load, 0.0)
        # per-broker average utilization percentage across its alive disks
        b_load = load.sum(axis=1, keepdims=True)
        b_cap = cap.sum(axis=1, keepdims=True)
        avg_pct = b_load / (b_cap + 1e-12)
        upper = avg_pct * t * cap
        lower = avg_pct * max(0.0, 2.0 - t) * cap
        from cruise_control_tpu.analyzer.goals.distribution import _band_violation

        return _band_violation(load, mask, upper, lower, load.sum())
