"""Analyzer layer: goal framework + batched TPU optimization engine.

Reference: cruise-control/.../analyzer/ (GoalOptimizer.java, goals/*).
"""

from cruise_control_tpu.analyzer.engine import Engine, OptimizerConfig
from cruise_control_tpu.analyzer.objective import (
    DEFAULT_CHAIN,
    GoalChain,
    balancedness_score,
)
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerResult
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.analyzer.proposals import ExecutionProposal, extract_proposals
from cruise_control_tpu.analyzer.scenario_eval import ScenarioEvaluator, ScenarioOutcome

__all__ = [
    "DEFAULT_CHAIN",
    "DEFAULT_OPTIONS",
    "Engine",
    "ExecutionProposal",
    "GoalChain",
    "GoalOptimizer",
    "OptimizationOptions",
    "OptimizerConfig",
    "OptimizerResult",
    "ScenarioEvaluator",
    "ScenarioOutcome",
    "balancedness_score",
    "extract_proposals",
]
