"""Scalarized goal-chain objective for the batched optimizer.

The reference optimizes goals *sequentially by priority*, letting every
already-optimized goal veto later moves (reference
analyzer/GoalOptimizer.java:437-461, analyzer/AnalyzerUtils.java:119).  A
batched annealer needs one scalar, so the chain is encoded
lexicographically (SURVEY §7 hard part (a)):

  objective = Σ_g  w_g · violation_g(state)  +  w_tie · Σ_g s_g · score_g(state)

with w_g decaying geometrically in priority order and every hard goal
boosted by HARD_BOOST so no weighted sum of soft improvements can pay for a
hard violation.  Violations are dimensionless fractions (each goal
normalizes by its own scale), which is what makes one scalar meaningful.

The balancedness score reported to users mirrors reference
KafkaCruiseControlUtils.balancednessCostByGoal:511-537 (priority weight
1.1x, strictness weight 1.5x for hard goals).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.goals import DEFAULT_GOAL_ORDER, GOALS_BY_NAME
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.aggregates import BrokerAggregates, compute_aggregates
from cruise_control_tpu.models.state import ClusterState

#: weight multiplier separating hard goals from the soft chain
HARD_BOOST = 1e4
#: geometric decay between adjacent priorities (reference uses priority order
#: as an absolute veto; 0.5 keeps ~2x headroom per rank while staying in f32
#: range across 19 goals)
PRIORITY_DECAY = 0.5
#: weight of the continuous tiebreaker scores relative to the smallest
#: violation weight
TIE_WEIGHT = 1e-3


@dataclasses.dataclass(frozen=True)
class GoalChain:
    """An ordered, weighted goal list (the reference's `default.goals`)."""

    goals: tuple[Goal, ...]
    weights: tuple[float, ...]  # violation weight per goal, same order

    @staticmethod
    def from_names(
        names: list[str] | None = None,
        *,
        hard_boost: float = HARD_BOOST,
        decay: float = PRIORITY_DECAY,
    ) -> "GoalChain":
        names = list(names) if names is not None else list(DEFAULT_GOAL_ORDER)
        goals = tuple(GOALS_BY_NAME[n] for n in names)
        weights = []
        for rank, g in enumerate(goals):
            w = decay**rank
            if g.hard:
                w *= hard_boost
            weights.append(w)
        return GoalChain(goals=goals, weights=tuple(weights))

    def evaluate(
        self,
        state: ClusterState,
        agg: BrokerAggregates | None = None,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        *,
        score_dtype: str = "float32",
    ):
        """Full evaluation: (scalar objective, violations[G], scores[G]).

        `score_dtype` (config analyzer.precision.score.dtype) selects the
        accumulation precision of the weighted objective sum ONLY: the
        per-goal violations/scores stay f32 (they feed early-stop compares
        and user reports), and the mixed-precision branch is taken only
        for a non-default dtype, so the default traced graph is
        byte-identical to the always-f32 one — the fp32 fallback pin.
        """
        if agg is None:
            agg = compute_aggregates(state)
        violations = jnp.stack([g.violation(state, agg, constraint) for g in self.goals])
        scores = jnp.stack([g.score(state, agg, constraint) for g in self.goals])
        w = jnp.asarray(self.weights, jnp.float32)
        if score_dtype != "float32":
            dt = jnp.dtype(score_dtype)
            obj = (
                (w.astype(dt) * violations.astype(dt)).sum().astype(jnp.float32)
                + TIE_WEIGHT
                * min(self.weights)
                * scores.astype(dt).sum().astype(jnp.float32)
            )
        else:
            obj = (w * violations).sum() + TIE_WEIGHT * min(self.weights) * scores.sum()
        return obj, violations, scores

    def hard_mask(self) -> np.ndarray:
        return np.asarray([g.hard for g in self.goals])

    def names(self) -> list[str]:
        return [g.name for g in self.goals]


def balancedness_score(
    violations: np.ndarray,
    chain: GoalChain,
    *,
    priority_weight: float = 1.1,
    strictness_weight: float = 1.5,
) -> float:
    """0-100 user-facing score (reference KafkaCruiseControlUtils.java:511-537).

    The reference sums weight = priority_weight^rank * (strictness_weight if
    hard) over *violated* goals and scales to 100.  A goal is "violated" here
    when its normalized violation exceeds 1e-6 — violations are fractions of
    cluster-wide totals computed in f32, whose noise floor at 500k-replica
    scale is ~1e-8..1e-7; the reference's per-goal epsilons serve the same
    role (its resource epsilons are far coarser than 1e-6 of total load).
    """
    n = len(chain.goals)
    weights = np.array(
        [
            priority_weight ** (n - 1 - i) * (strictness_weight if g.hard else 1.0)
            for i, g in enumerate(chain.goals)
        ],
        np.float64,
    )
    total = weights.sum()
    violated = np.asarray(violations) > 1e-6
    return float(100.0 * (1.0 - weights[violated].sum() / total))


DEFAULT_CHAIN = GoalChain.from_names()
