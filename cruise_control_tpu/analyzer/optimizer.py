"""GoalOptimizer facade — compute optimization proposals for a cluster model.

Reference: analyzer/GoalOptimizer.java:416-487 (per-goal sequential
optimize + stats + diff) and analyzer/OptimizerResult.java:31.  The TPU
rebuild runs the whole weighted goal chain at once through the batched
annealing engine and reports per-goal violations before/after, cluster
stats, the balancedness score, and the proposal diff.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from cruise_control_tpu.analyzer.engine import Engine, OptimizerConfig
from cruise_control_tpu.analyzer.objective import (
    DEFAULT_CHAIN,
    GoalChain,
    balancedness_score,
)
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.analyzer.proposals import ExecutionProposal, extract_proposals
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.state import ClusterState, validate
from cruise_control_tpu.models.stats import ClusterStats, compute_stats


@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """What an optimization run produced (reference analyzer/OptimizerResult.java:31)."""

    proposals: list[ExecutionProposal]
    state_before: ClusterState
    state_after: ClusterState
    stats_before: ClusterStats
    stats_after: ClusterStats
    goal_names: list[str]
    violations_before: np.ndarray  # f32[G]
    violations_after: np.ndarray  # f32[G]
    balancedness_before: float
    balancedness_after: float
    objective_before: float
    objective_after: float
    wall_seconds: float
    history: list[dict]

    @property
    def num_inter_broker_moves(self) -> int:
        return sum(1 for p in self.proposals if p.has_replica_action)

    @property
    def num_leadership_moves(self) -> int:
        return sum(
            1 for p in self.proposals if p.has_leader_action and not p.has_replica_action
        )

    @property
    def data_to_move(self) -> float:
        return sum(p.inter_broker_data_to_move for p in self.proposals)

    def violated_goals_after(self, tol: float = 1e-9) -> list[str]:
        return [n for n, v in zip(self.goal_names, self.violations_after) if v > tol]

    def summary(self) -> dict:
        return {
            "numReplicaMovements": self.num_inter_broker_moves,
            "numLeaderMovements": self.num_leadership_moves,
            "dataToMoveMB": self.data_to_move,
            "balancednessBefore": self.balancedness_before,
            "balancednessAfter": self.balancedness_after,
            "objectiveBefore": self.objective_before,
            "objectiveAfter": self.objective_after,
            "violatedGoalsAfter": self.violated_goals_after(),
            "wallSeconds": self.wall_seconds,
        }


class GoalOptimizer:
    """Entry point the service layer calls (reference GoalOptimizer.optimizations:416)."""

    def __init__(
        self,
        chain: GoalChain = DEFAULT_CHAIN,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        config: OptimizerConfig = OptimizerConfig(),
    ):
        import jax

        self.chain = chain
        self.constraint = constraint
        self.config = config
        #: engines cached per (ClusterShape, search config) — rebinding data
        #: is free, recompiling is not (reference amortizes the same way via
        #: its proposal precompute loop, GoalOptimizer.java:124-175)
        self._engines: dict = {}
        # one persistent jitted program for objective+violations+stats:
        # eager per-op dispatch on large models costs orders of magnitude
        # more than the computation itself
        self._report = jax.jit(
            lambda s: (
                self.chain.evaluate(s, constraint=self.constraint)[:2],
                compute_stats(s),
            )
        )

    def _engine_for(
        self, state: ClusterState, options: OptimizationOptions, config: OptimizerConfig
    ) -> Engine:
        key = (state.shape, config)
        engine = self._engines.get(key)
        if engine is None:
            engine = Engine(
                state, self.chain, constraint=self.constraint, options=options, config=config
            )
            self._engines[key] = engine
        else:
            engine.rebind(state, options)
        return engine

    def optimize(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        *,
        verbose: bool = False,
        config: OptimizerConfig | None = None,
    ) -> OptimizerResult:
        t0 = time.monotonic()
        validate(state)
        engine = self._engine_for(state, options, config or self.config)
        (obj_b, viol_b), stats_b = self._report(state)
        final, history = engine.run(verbose=verbose)
        (obj_a, viol_a), stats_a = self._report(final)
        validate(final)
        viol_b = np.asarray(viol_b)
        viol_a = np.asarray(viol_a)
        wall = time.monotonic() - t0
        return OptimizerResult(
            proposals=extract_proposals(state, final),
            state_before=state,
            state_after=final,
            stats_before=stats_b,
            stats_after=stats_a,
            goal_names=self.chain.names(),
            violations_before=viol_b,
            violations_after=viol_a,
            balancedness_before=balancedness_score(viol_b, self.chain),
            balancedness_after=balancedness_score(viol_a, self.chain),
            objective_before=float(obj_b),
            objective_after=float(obj_a),
            wall_seconds=wall,
            history=history,
        )
