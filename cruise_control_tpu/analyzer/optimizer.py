"""GoalOptimizer facade — compute optimization proposals for a cluster model.

Reference: analyzer/GoalOptimizer.java:416-487 (per-goal sequential
optimize + stats + diff) and analyzer/OptimizerResult.java:31.  The TPU
rebuild runs the whole weighted goal chain at once through the batched
annealing engine and reports per-goal violations before/after, cluster
stats, the balancedness score, and the proposal diff.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from cruise_control_tpu.analyzer.engine import Engine, OptimizerConfig
from cruise_control_tpu.analyzer.objective import (
    DEFAULT_CHAIN,
    GoalChain,
    balancedness_score,
)
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.common.blackbox import (
    RECORDER as _BLACKBOX,
    blackbox_context,
)
from cruise_control_tpu.common.dispatch import count_dispatch
from cruise_control_tpu.analyzer.proposals import (
    ExecutionProposal,
    ProposalSet,
    extract_proposals,
)
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.state import ClusterState, validate
from cruise_control_tpu.models.stats import ClusterStats, compute_stats


@dataclasses.dataclass(frozen=True)
class OptimizerResult:
    """What an optimization run produced (reference analyzer/OptimizerResult.java:31)."""

    proposals: list[ExecutionProposal]
    state_before: ClusterState
    state_after: ClusterState
    stats_before: ClusterStats
    stats_after: ClusterStats
    goal_names: list[str]
    violations_before: np.ndarray  # f32[G]
    violations_after: np.ndarray  # f32[G]
    balancedness_before: float
    balancedness_after: float
    objective_before: float
    objective_after: float
    wall_seconds: float
    history: list[dict]

    @property
    def num_inter_broker_moves(self) -> int:
        # ProposalSet answers from its columns without materializing the
        # ~100k ExecutionProposal objects; plain lists (tests, ad-hoc
        # results) take the object path
        ps = self.proposals
        if isinstance(ps, ProposalSet):
            return ps.num_inter_broker_moves
        return sum(1 for p in ps if p.has_replica_action)

    @property
    def num_leadership_moves(self) -> int:
        ps = self.proposals
        if isinstance(ps, ProposalSet):
            return ps.num_leadership_moves
        return sum(1 for p in ps if p.has_leader_action and not p.has_replica_action)

    @property
    def data_to_move(self) -> float:
        ps = self.proposals
        if isinstance(ps, ProposalSet):
            return ps.data_to_move
        return sum(p.inter_broker_data_to_move for p in ps)

    @property
    def degraded(self) -> bool:
        """True when this result came from the CPU greedy fallback because
        the device path was unavailable (supervisor breaker open) or
        failed with a classified device fault — the history carries a
        `degraded` record with the reason and failure class."""
        return any(h.get("degraded") for h in self.history)

    def violated_goals_after(self, tol: float = 1e-6) -> list[str]:
        """Default tol matches balancedness_score's goal-satisfied epsilon
        (analyzer/objective.py) — a response must not claim balancedness 100
        while listing goals 'violated' by f32 noise."""
        return [n for n, v in zip(self.goal_names, self.violations_after) if v > tol]

    def summary(self) -> dict:
        return {
            "numReplicaMovements": self.num_inter_broker_moves,
            "numLeaderMovements": self.num_leadership_moves,
            "dataToMoveMB": self.data_to_move,
            "balancednessBefore": self.balancedness_before,
            "balancednessAfter": self.balancedness_after,
            "objectiveBefore": self.objective_before,
            "objectiveAfter": self.objective_after,
            "violatedGoalsAfter": self.violated_goals_after(),
            "wallSeconds": self.wall_seconds,
            "degraded": self.degraded,
        }


def parse_parallel_mode(mode: str) -> tuple[int, int] | None:
    """Validate "single" / "sharded" / "grid:RxM"; returns (R, M) for grid
    modes, None otherwise.  The single source of truth for the mode syntax
    (the config validator delegates here)."""
    import re

    if mode in ("single", "sharded"):
        return None
    m = re.fullmatch(r"grid:([1-9]\d*)x([1-9]\d*)", str(mode))
    if m:
        return int(m.group(1)), int(m.group(2))
    raise ValueError(
        f"tpu.parallel.mode must be single | sharded | grid:RxM, got {mode!r}"
    )


def _release_engine(engine) -> None:
    """Free an evicted engine's device buffers (HBM) explicitly.

    Only via the engine's own release() — it knows which statics arrays
    are engine-derived vs caller-owned (deleting blindly would destroy the
    caller's ClusterState buffers, still alive as result.state_before and
    in sibling engines).  Engines without release() fall back to GC."""
    release = getattr(engine, "release", None)
    if release is not None:
        release()


class GoalOptimizer:
    """Entry point the service layer calls (reference GoalOptimizer.optimizations:416)."""

    def __init__(
        self,
        chain: GoalChain = DEFAULT_CHAIN,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        config: OptimizerConfig = OptimizerConfig(),
        parallel_mode: str = "single",
        mesh_max_devices: int = 0,
        model_shard_min_partitions: int = 0,
        balancedness_weights: tuple[float, float] = (1.1, 1.5),
        engine_cache_size: int = 8,
        sensors=None,
        shape_bucket=None,
        supervisor=None,
        degraded_budget_s: float = 30.0,
        tracer=None,
        profiler_dir: str | None = None,
        prewarm_store=None,
        peak_tracker=None,
        mesh_ft=None,
    ):
        """parallel_mode (config key tpu.parallel.mode): "single" (one
        device), "sharded" (candidate axis sharded over the mesh,
        parallel/sharded.py), or "grid:RxM" (restart portfolio over model
        shards, parallel/grid.py) — both through the shared mesh engine
        layer (parallel/mesh.py).  mesh_max_devices (config key
        tpu.mesh.max.devices) caps how many visible devices the mesh is
        built from; 0 (default) uses them all.

        model_shard_min_partitions (config key
        tpu.mesh.model.shard.min.partitions): real partition count at or
        above which the mesh modes shard the flattened MODEL over the
        model axis (parallel/model_shard.py) instead of replicating it —
        per-chip model memory and per-step row FLOPs drop ~1/n with
        byte-identical placements.  0 (default) keeps the replicated
        model, which wins on collective volume for small clusters.

        balancedness_weights = (priority_weight, strictness_weight) for the
        0-100 balancedness score (reference AnalyzerConfig
        goal.balancedness.{priority,strictness}.weight).

        engine_cache_size (config key tpu.engine.cache.size) bounds the
        per-(shape, config) compiled-engine LRU; evicted engines have
        their device buffers released.  sensors: optional SensorRegistry
        receiving engine-cache hit/miss counters and a size gauge.

        shape_bucket (config keys tpu.shape.bucket.*): ShapeBucketPolicy
        the MULTI-DEVICE engines pad their inputs under, so shard layouts
        derive from bucketed shapes and exact-vs-bucketed builds shard
        identically.  Defaults to the service default policy; the
        single-device path needs no padding here because model builds are
        already bucketed upstream and the engine masks padding anyway.

        supervisor (config keys tpu.supervisor.*): DeviceSupervisor every
        device-path invocation runs under — bounded budget, failure
        classification, retry, circuit breaker (common/device_watchdog.py).
        While the breaker is open (or when a call fails with a classified
        device failure) `optimize` transparently serves a CPU greedy
        result tagged degraded=True instead of hanging or failing; None
        (the default, offline/test usage) keeps the direct path with zero
        behavior change.  degraded_budget_s caps the greedy fallback's
        wall clock (config tpu.supervisor.degraded.greedy.budget.s).

        tracer (config trace.*): flight-recorder Tracer every optimize
        call opens an `analyzer.optimize` span on, with the run's timing
        record (device_s / engine_cache_hit / bucket / degraded) attached
        as attributes; defaults to the process-wide common.trace.TRACER.

        profiler_dir (config tpu.profiler.*): when set, every engine run
        is wrapped in a jax.profiler trace dumped there — the XLA-level
        view for slow-run forensics.  None (default) profiles nothing.

        prewarm_store (config tpu.prewarm.*, analyzer/prewarm.py): the
        durable boot-prewarm manifest + AOT artifact store.  When bound,
        every engine build/rebind records its (bucket, config) working
        set, single-device engines try/save AOT-serialized fused
        programs through their warm pool, and `start_up()` replays the
        manifest so a restart's active buckets compile before the first
        proposal.  None (offline/test/ad-hoc optimizers) records and
        loads nothing.

        peak_tracker (common/profiling.PeakLiveBytesTracker): when bound,
        every optimize records the post-run per-device live bytes into
        the run's shape-bucket cell of the
        `tpu.device.peak-live-bytes-by-bucket` collector.

        mesh_ft (config keys tpu.mesh.ft.*, parallel/ft.py): the mesh
        fault-tolerance controller — per-width breakers, degrade
        episodes, and the slice-boundary checkpoint cadence.  Supervised
        mesh modes default to a controller of their own (checkpointing
        off) so a classified mesh failure degrades the WIDTH ladder
        (narrower mesh -> plain engine -> CPU greedy) instead of opening
        the single-device breaker; pass an explicit controller to wire
        config/sensors, or one with enabled=False to restore the pre-FT
        straight-to-greedy behavior."""
        import threading

        import jax

        self.chain = chain
        self.constraint = constraint
        self.config = config
        self.parallel_mode = parallel_mode
        if mesh_max_devices < 0:
            raise ValueError(
                f"mesh_max_devices must be >= 0, got {mesh_max_devices}"
            )
        self.mesh_max_devices = mesh_max_devices
        if model_shard_min_partitions < 0:
            raise ValueError(
                f"model_shard_min_partitions must be >= 0, got "
                f"{model_shard_min_partitions}"
            )
        self.model_shard_min_partitions = model_shard_min_partitions
        self.balancedness_weights = balancedness_weights
        self._grid_shape = parse_parallel_mode(parallel_mode)
        # device probing stays lazy for the single-device default: only the
        # mesh modes need a count, and jax.devices() on a wedged backend
        # hangs outside any supervisor seam (the MULTICHIP_r05 class)
        if self._grid_shape is not None:
            r, m = self._grid_shape
            n_avail = len(self._mesh_devices())
            if n_avail < r * m:
                raise ValueError(
                    f"tpu.parallel.mode={self.parallel_mode!r} needs "
                    f"{r * m} devices, host has {n_avail} "
                    f"(tpu.mesh.max.devices={mesh_max_devices})"
                )
        elif self.parallel_mode != "single" and len(self._mesh_devices()) < 2:
            # single-chip host: sharded degenerates to the local engine
            self.parallel_mode = "single"
        if engine_cache_size < 1:
            raise ValueError(
                f"engine_cache_size must be >= 1, got {engine_cache_size}"
            )
        from collections import OrderedDict

        #: engines cached per (ClusterShape, search config) in LRU order —
        #: rebinding data is free, recompiling is not (reference amortizes
        #: the same way via its proposal precompute loop,
        #: GoalOptimizer.java:124-175).  Bounded: under topology churn an
        #: unbounded map accretes one full model generation of HBM per
        #: bucket transition; eviction releases the engine's buffers.
        self._engines: OrderedDict = OrderedDict()
        self._parallel_engines: OrderedDict = OrderedDict()
        self._cache_capacity = engine_cache_size
        self._cache_lock = threading.Lock()
        self.sensors = sensors
        self.supervisor = supervisor
        self.degraded_budget_s = degraded_budget_s
        self.prewarm_store = prewarm_store
        self.peak_tracker = peak_tracker
        from cruise_control_tpu.common.trace import TRACER

        self.tracer = tracer if tracer is not None else TRACER
        self.profiler_dir = profiler_dir
        #: per-bucket cumulative cold-start attribution: bucket key ->
        #: {compiles, coldWallSeconds, buildSeconds}.  A cache-miss run's
        #: wall INCLUDES its lazy XLA compile (engine_build_s is host
        #: construction only), so coldWallSeconds is the honest per-bucket
        #: compile+first-run bill — the number ROADMAP item 2's persistent
        #: compile cache must drive toward zero.  Guarded by _cache_lock.
        self._compile_attribution: dict[str, dict] = {}
        #: breaker open-epoch last seen — caches are purged once per open
        #: transition (pull-based: no callback registration to leak across
        #: the facade's short-lived per-request optimizers)
        self._breaker_epoch = supervisor.open_epoch if supervisor is not None else 0
        #: mesh fault tolerance (parallel/ft.py): supervised mesh modes
        #: get a default controller so device loss degrades the width
        #: ladder; "single" mode carries None (zero behavior change)
        if (
            mesh_ft is None
            and self.parallel_mode != "single"
            and supervisor is not None
        ):
            from cruise_control_tpu.parallel.ft import MeshFtController

            mesh_ft = MeshFtController(sensors=sensors)
        self._mesh_ft = mesh_ft if self.parallel_mode != "single" else None
        self._report_cpu = None  # lazy CPU twin of _report (degraded path)
        from cruise_control_tpu.models.state import DEFAULT_BUCKET_POLICY

        self.shape_bucket = (
            shape_bucket if shape_bucket is not None else DEFAULT_BUCKET_POLICY
        )
        #: compile-vs-rebind outcome counters (the churn bench and tests
        #: assert "zero compiles across a churned generation" through these)
        self.engine_cache_hits = 0
        self.engine_cache_misses = 0
        # one persistent jitted program for objective+violations+stats:
        # eager per-op dispatch on large models costs orders of magnitude
        # more than the computation itself
        self._report = jax.jit(
            lambda s: (
                self.chain.evaluate(s, constraint=self.constraint)[:2],
                compute_stats(s),
            )
        )

    # ------------------------------------------------------------------
    # engine cache (bounded LRU, explicit HBM release on eviction)
    # ------------------------------------------------------------------

    @property
    def cache_size(self) -> int:
        """Compiled engines currently resident (plain + parallel) — public
        beside engine_cache_hits/misses: the /fleet rollup and the
        fleet-smoke bench gate read it."""
        return len(self._engines) + len(self._parallel_engines)

    def _record(self, hit: bool, *, count: bool = True) -> None:
        if count:
            if hit:
                self.engine_cache_hits += 1
            else:
                self.engine_cache_misses += 1
            if self.sensors is not None:
                name = "hits" if hit else "misses"
                self.sensors.counter(f"analyzer.engine-cache-{name}").inc()
        if self.sensors is not None:
            self.sensors.gauge("analyzer.engine-cache-size").set(self.cache_size)

    def _cache_get(self, cache, key):
        """Fetch + pin: the engine's busy count is raised under the lock so
        a concurrent eviction never hard-releases an engine mid-run (the
        facade shares one optimizer between request threads and the
        precompute/prewarm thread).  Callers MUST pair with _unpin."""
        with self._cache_lock:
            engine = cache.get(key)
            if engine is not None:
                cache.move_to_end(key)
                engine._cc_busy = getattr(engine, "_cc_busy", 0) + 1
            return engine

    def _unpin(self, engine) -> None:
        # under the same lock as the pinning read-modify-writes: an
        # unlocked decrement could clobber a concurrent _cache_get pin
        # (freeing a live engine) or lose a decrement (leaking it forever)
        with self._cache_lock:
            engine._cc_busy = max(0, getattr(engine, "_cc_busy", 1) - 1)

    def _cache_put(self, cache, key, engine, *, if_absent: bool = False) -> bool:
        """Insert pinned + evict LRU overflow; returns whether `engine`
        was published.  With if_absent=True an existing entry wins and the
        offered engine is released instead (it was never published, so no
        run can be using it) — prewarm's lost-race path.  Evicted (or
        silently replaced) engines are hard-released only when no thread
        holds a pin; a still-busy engine is dropped from the cache and
        left to GC — a rare deferred release beats deleting buffers under
        a live run."""
        released = []
        published = True
        with self._cache_lock:
            old = cache.get(key)
            if old is not None and old is not engine:
                if if_absent:
                    published = False
                else:
                    released.append(old)  # replaced under the same key
            if published:
                engine._cc_busy = getattr(engine, "_cc_busy", 0) + 1
                cache[key] = engine
                cache.move_to_end(key)
                while len(cache) > self._cache_capacity:
                    released.append(cache.popitem(last=False)[1])
        if not published:
            _release_engine(engine)
        for e in released:
            if not getattr(e, "_cc_busy", 0):
                _release_engine(e)
        return published

    def _engine_for(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        config: OptimizerConfig,
        *,
        count: bool = True,
        prior=None,
    ) -> tuple[Engine, dict]:
        """Cached engine for (shape, config) + a compile-vs-rebind outcome
        record ({engine_cache_hit, engine_build_s}) for the result timing.
        The engine comes back PINNED — the caller unpins after run().

        engine_build_s is host construction/rebind time only: the jitted
        programs compile lazily at first run, so the XLA compile itself
        lands in the run's device wall — engine_cache_hit (False exactly
        when that compile will be paid) is the compile signal."""
        key = (state.shape, config)
        engine = self._cache_get(self._engines, key)
        hit = engine is not None
        t0 = time.monotonic()
        if hit:
            try:
                engine.rebind(state, options, prior=prior)
            except BaseException:
                # a failed rebind (bad options mask, device error) must not
                # leave the _cache_get pin behind — a stuck pin exempts the
                # engine from hard release on eviction forever
                self._unpin(engine)
                raise
        else:
            engine = Engine(
                state, self.chain, constraint=self.constraint, options=options,
                config=config, prior=prior, prewarm_store=self.prewarm_store,
            )
            self._cache_put(self._engines, key, engine)
        self._record(hit, count=count)
        self._note_prewarm(engine, config)
        return engine, dict(
            engine_cache_hit=hit, engine_build_s=round(time.monotonic() - t0, 6)
        )

    def _note_prewarm(self, engine, config, *, parallel_mode: str = "single") -> None:
        """Record this engine's (bucket, config) in the boot-prewarm
        manifest — the ACTIVE working set a restart replays.  Best-effort;
        hits refresh recency (throttled on disk), misses write through."""
        store = self.prewarm_store
        if store is None:
            return
        try:
            # the partition-replica table's width (max observed RF) is the
            # one data-dependent aval axis the shape alone does not pin —
            # a prewarm at the wrong width compiles the wrong program
            inner = getattr(engine, "engine", engine)  # mesh engines wrap one
            max_rf = int(inner.statics.part_replicas.shape[1])
            store.note(
                inner.shape, max_rf, config, parallel_mode=parallel_mode
            )
        except Exception:  # noqa: BLE001 — the manifest is best-effort
            pass

    @staticmethod
    def _parallel_key(shape, config, devices):
        """Parallel engines cache per (shape, config, device-id set): the
        mesh fault-tolerance ladder builds engines over SURVIVOR subsets,
        and a reduced-width engine must never be served as (or evicted
        by) the full-width one."""
        return (shape, config, tuple(int(d.id) for d in devices))

    def _parallel_engine(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        config: OptimizerConfig,
        *,
        devices=None,
    ):
        """Multi-device engine per parallel_mode, cached per (shape,
        config, devices) with a data rebind like _engine_for — recompiling
        the sharded programs per request would cost seconds to minutes.
        Shard layouts derive from the (bucketed) global shape, but max_rf
        remains data-dependent; a rebind that changes the local shapes
        falls back to building a fresh engine.  `devices` (mesh ft) builds
        over a survivor subset; None = every mesh device."""
        if devices is None:
            devices = self._mesh_devices()
        key = self._parallel_key(state.shape, config, devices)
        engine = self._cache_get(self._parallel_engines, key)
        t0 = time.monotonic()
        if engine is not None:
            try:
                engine = engine.rebind(state, options)
                self._record(True)
                self._note_prewarm(engine, config, parallel_mode=self.parallel_mode)
                return engine, dict(
                    engine_cache_hit=True,
                    engine_build_s=round(time.monotonic() - t0, 6),
                )
            except ValueError:
                self._unpin(engine)  # local shard shapes changed: rebuild
            except BaseException:
                self._unpin(engine)  # pin must not outlive a failed rebind
                raise
        engine = self._build_parallel_engine(state, options, config, devices=devices)
        self._cache_put(self._parallel_engines, key, engine)
        self._record(False)
        self._note_prewarm(engine, config, parallel_mode=self.parallel_mode)
        return engine, dict(
            engine_cache_hit=False, engine_build_s=round(time.monotonic() - t0, 6)
        )

    def has_engine_for(
        self, shape, *, config: OptimizerConfig | None = None
    ) -> bool:
        """True when a compiled engine for (shape, config) is cached —
        lets the facade's precompute loop skip the padded-model build when
        the next bucket is already warm."""
        cfg = config or self.config
        with self._cache_lock:
            return (shape, cfg) in self._engines or any(
                k[0] == shape and k[1] == cfg for k in self._parallel_engines
            )

    def prewarm(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        *,
        config: OptimizerConfig | None = None,
        priority: int = 0,
    ) -> None:
        """Build + background-compile the engine for `state`'s shape without
        running it (the facade pre-warms the NEXT shape bucket with a padded
        model so a bucket overflow hits a warm engine instead of a cold
        compile).  Build-only, never rebind: if an engine for the shape
        already exists — including one a foreground request inserted while
        we were building — it is left untouched, because rebinding it to
        this (possibly stale, zero-padded) snapshot could swap statics
        under a live run.  Does not touch the hit/miss counters.

        Supervised like optimize: with a breaker open nothing is built
        (pre-warming a wedged device only queues more hangs), and a hang
        or device failure during the build is bounded + classified instead
        of wedging the facade's precompute thread forever.  Degradation
        here has no fallback — a skipped prewarm just means the next
        bucket overflow pays its compile.

        `priority` orders this prewarm's compiles on the shared warm pool
        (boot prewarm: the ACTIVE bucket at 0, manifest speculation after
        it, the facade's next-bucket speculation last)."""
        sup = self.supervisor
        if sup is None:
            self._prewarm_on_device(state, options, config=config, priority=priority)
            return
        from cruise_control_tpu.common.device_watchdog import DeviceDegradedError

        self._maybe_purge_after_open()
        if not sup.available():
            return
        try:
            sup.call(
                lambda: self._prewarm_on_device(
                    state, options, config=config, priority=priority
                ),
                op="prewarm",
            )
        except DeviceDegradedError:
            self._maybe_purge_after_open()

    def _prewarm_on_device(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        *,
        config: OptimizerConfig | None = None,
        priority: int = 0,
    ) -> None:
        cfg = config or self.config
        parallel = self.parallel_mode != "single"
        key = (
            self._parallel_key(state.shape, cfg, self._mesh_devices())
            if parallel
            else (state.shape, cfg)
        )
        cache = self._parallel_engines if parallel else self._engines
        with self._cache_lock:
            if key in cache:
                return
        # mesh engines warm through the SAME pool as the plain engine
        # (engine.start_warm_pool) — prewarm covers every parallel mode
        engine = (
            self._build_parallel_engine(state, options, cfg)
            if parallel
            else Engine(
                state, self.chain, constraint=self.constraint,
                options=options, config=cfg,
                prewarm_store=self.prewarm_store,
            )
        )
        if not self._cache_put(cache, key, engine, if_absent=True):
            return  # a foreground request built the engine first
        self._record(False, count=False)
        try:
            engine.precompile_async(priority=priority)
        finally:
            self._unpin(engine)

    def _mesh_devices(self):
        """The devices the mesh engine layer may use: every visible device,
        optionally capped by tpu.mesh.max.devices."""
        import jax

        devices = jax.devices()
        if self.mesh_max_devices:
            devices = devices[: self.mesh_max_devices]
        return devices

    def _build_parallel_engine(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        config: OptimizerConfig,
        *,
        devices=None,
    ):
        """Mesh engine for the current parallel_mode over `devices` (None
        = every mesh device, today's exact layout).  A survivor subset
        (mesh ft) keeps the grid's RESTART axis fixed — checkpointed
        chains must map 1:1 onto the rebuilt mesh — and shrinks the MODEL
        axis to what the subset can carry."""
        from cruise_control_tpu.parallel.grid import GridEngine, grid_mesh
        from cruise_control_tpu.parallel.sharded import ShardedEngine, model_mesh

        explicit = devices is not None
        if devices is None:
            devices = self._mesh_devices()
        if self.parallel_mode == "sharded":
            return ShardedEngine(
                state, self.chain, mesh=model_mesh(devices),
                constraint=self.constraint, options=options, config=config,
                bucket=self.shape_bucket,
                model_shard_min_partitions=self.model_shard_min_partitions,
            )
        r, m = self._grid_shape
        if explicit:
            m = len(devices) // r
            if m < 1:
                raise ValueError(
                    f"{len(devices)} devices cannot carry a "
                    f"{r}-restart grid"
                )
        return GridEngine(
            state, self.chain, mesh=grid_mesh(r, m, devices),
            constraint=self.constraint, options=options, config=config,
            bucket=self.shape_bucket,
            model_shard_min_partitions=self.model_shard_min_partitions,
        )

    def optimize(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        *,
        verbose: bool = False,
        config: OptimizerConfig | None = None,
        initial_placement=None,
        prior=None,
    ) -> OptimizerResult:
        """Run the goal chain; supervised when a DeviceSupervisor is wired.

        `initial_placement` / `prior` are the streaming controller's
        warm-start inputs (engine.run warm carry + the learned
        move-acceptance prior folded into the sampling plan); both are
        single-device-mode only and ignored by the CPU-greedy degraded
        fallback, which always answers from the current placement.

        Unsupervised (offline/test default) this IS `_optimize_on_device`.
        Supervised, the whole device body — input checks, engine build/
        rebind, compile, anneal, report, extraction — runs inside one
        bounded, classified supervisor call; a breaker already open skips
        the device entirely.  Classified failures (hang / compile / OOM /
        exhausted transient retries) degrade to the CPU greedy path;
        application errors (bad states, bad option masks) propagate
        unchanged so a malformed request can neither degrade the service
        nor get silently served a greedy answer.

        Traced: every call is an `analyzer.optimize` span carrying the
        run's timing record (device_s / blocking_syncs / engine_cache_hit
        / bucket) and degradation verdict as attributes — the flight
        recorder's analyzer stage."""
        cfg = config or self.config
        with self.tracer.span("analyzer.optimize", component="analyzer") as sp:
            if _BLACKBOX.enabled:
                # stamp the dispatch context the black-box spool's leaf
                # records (supervised / device-op / engine-slice) cannot
                # know themselves: which bucket, which search config,
                # which parallel mode — the "what was it doing" half of a
                # hang post-mortem (common/blackbox.py)
                import hashlib

                bucketed = (
                    self.shape_bucket.bucket_shape(state.shape)
                    if self.shape_bucket is not None
                    else state.shape
                )
                ctx = blackbox_context(
                    bucket=self._bucket_key(bucketed),
                    config_fp=hashlib.sha1(
                        repr(cfg).encode()
                    ).hexdigest()[:12],
                    parallel_mode=self.parallel_mode,
                )
            else:
                import contextlib

                ctx = contextlib.nullcontext()
            with ctx:
                result = self._optimize_routed(
                    state, options, verbose, cfg,
                    initial_placement=initial_placement, prior=prior,
                )
            timing = next((h for h in result.history if h.get("timing")), {})
            sp.set(
                parallel_mode=self.parallel_mode,
                degraded=result.degraded,
                wall_s=round(result.wall_seconds, 6),
                num_proposals=len(result.proposals),
                # final per-goal violations ON the span: a /trace replay
                # shows the run's goal quality even with the decision
                # ledger disabled (objective/balancedness beside them)
                objective_after=round(result.objective_after, 6),
                balancedness_after=round(result.balancedness_after, 3),
                goal_violations_after={
                    n: round(float(v), 6)
                    for n, v in zip(
                        result.goal_names, np.asarray(result.violations_after)
                    )
                },
                **{
                    k: timing.get(k)
                    for k in (
                        "device_s", "blocking_syncs", "host_extract_s",
                        "engine_cache_hit", "engine_build_s", "bucket",
                        "mesh_shape", "collective_bytes",
                        # segmented (preemptible) execution under the
                        # device scheduler: how many wall-bounded slices
                        # this anneal dispatched as
                        "segmented", "segments",
                        # convergence diagnostics summary (trajectory,
                        # acceptance by kind, prior usage, final per-goal
                        # violations) when OptimizerConfig.diagnostics
                        "convergence",
                    )
                    if timing.get(k) is not None
                },
            )
            return result

    def _optimize_routed(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        verbose: bool,
        cfg: OptimizerConfig,
        *,
        initial_placement=None,
        prior=None,
    ) -> OptimizerResult:
        """Supervision routing (the pre-trace `optimize` body): device
        path under the supervisor, CPU greedy degradation on breaker-open
        or classified failure — split out so the span wrapper observes
        every route's result uniformly."""
        sup = self.supervisor
        if sup is None:
            return self._optimize_on_device(
                state, options, verbose=verbose, config=cfg,
                initial_placement=initial_placement, prior=prior,
            )
        from cruise_control_tpu.common.device_watchdog import DeviceDegradedError

        self._maybe_purge_after_open()
        if not sup.available():
            return self._optimize_degraded(state, options, cfg, reason="breaker-open")
        ft = self._mesh_ft
        if self.parallel_mode != "single" and ft is not None and ft.enabled:
            return self._optimize_mesh_ft(state, options, verbose, cfg, sup, ft)
        try:
            return sup.call(
                lambda: self._optimize_on_device(
                    state, options, verbose=verbose, config=cfg,
                    initial_placement=initial_placement, prior=prior,
                ),
                op="optimize",
            )
        except DeviceDegradedError as e:
            self._maybe_purge_after_open()
            return self._optimize_degraded(
                state, options, cfg,
                reason=e.failure_class.value, cause=e,
            )

    # ------------------------------------------------------------------
    # mesh fault tolerance (degrade-and-resume width ladder)
    # ------------------------------------------------------------------

    def _reduced_mesh_devices(self, survivors, *, below: int):
        """The next rung's device list after a failure at width `below`:
        the widest power-of-two MODEL-axis width the survivors can carry
        — strictly below the failed width even when attribution named no
        suspect (a blind halving still excludes a wedged chip half the
        time).  Grid modes keep the RESTART axis fixed (checkpointed
        chains must map 1:1 onto the rebuilt mesh) and shrink the model
        axis.  None = no mesh width survives (fall to the plain rung)."""
        r = self._grid_shape[0] if self._grid_shape is not None else 1
        cap = min(len(survivors), below - 1)
        if cap < max(2, r):
            return None
        m = 1
        while m * 2 * r <= cap:
            m *= 2
        return list(survivors[: r * m])

    def _purge_parallel_for_mesh_failure(self, suspect_ids, failed_ids) -> None:
        """Drop parallel engines whose mesh touches the failed chips: a
        lost/wedged device owns buffers of unknown integrity, but engines
        on disjoint survivor subsets — and every single-device engine —
        stay cached (the scoped-purge contract tests/test_mesh_ft.py
        pins)."""
        bad = set(suspect_ids) if suspect_ids else set(failed_ids)
        released = []
        with self._cache_lock:
            for key in [
                k for k in self._parallel_engines if bad & set(k[2])
            ]:
                released.append(self._parallel_engines.pop(key))
        for e in released:
            if not getattr(e, "_cc_busy", 0):
                _release_engine(e)
        self._record(False, count=False)  # refresh the size gauge

    def _optimize_mesh_ft(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        verbose: bool,
        cfg: OptimizerConfig,
        sup,
        ft,
    ) -> OptimizerResult:
        """The mesh width ladder: attempt the widest usable rung, and on a
        classified MESH failure (device lost / collective stall) rebuild
        over the survivors at the next lower power-of-two width, resuming
        from the last slice-boundary checkpoint when one exists.  Every
        mesh attempt runs under that WIDTH's breaker (`sup.call(breaker=
        ...)`) with attribution armed (`mesh_devices=`); non-mesh
        classified failures keep today's straight-to-greedy behavior.
        When no width survives: plain engine under the single-device
        breaker, then CPU greedy — the pre-existing ladder."""
        import contextlib

        from cruise_control_tpu.analyzer.engine import (
            SegmentContext,
            current_segment_context,
            segmented_execution,
        )
        from cruise_control_tpu.common.device_watchdog import (
            CheckpointClock,
            DeviceDegradedError,
            MESH_FAILURE_CLASSES,
            checkpoint_clock_scope,
        )
        from cruise_control_tpu.parallel.ft import CheckpointSlot

        devices = list(self._mesh_devices())
        full_width = len(devices)
        slot = CheckpointSlot()
        clock = CheckpointClock()
        resume = None
        lost: list[int] = []
        last_mesh_error = None
        while devices is not None:
            width = len(devices)
            brk = ft.acquire_width(width)
            if brk is None:  # this width's breaker is open, probe not due
                devices = self._reduced_mesh_devices(devices, below=width)
                continue
            every = ft.checkpoint_every_slices
            if every > 0:
                # install (or augment) the ambient segmented-execution
                # request so mesh slice boundaries feed carry snapshots
                # into the slot; the scheduler's budget and pause
                # callback are preserved.  every=0 installs NOTHING —
                # the off path is byte-for-byte today's dispatch stream.
                ambient = current_segment_context()
                seg_ctx = SegmentContext(
                    ambient.slice_budget_s if ambient is not None else float("inf"),
                    ambient.checkpoint if ambient is not None else None,
                    snapshot_every=every,
                    snapshot_sink=slot.offer,
                    checkpoint_clock=clock,
                )
                scope = segmented_execution(seg_ctx)
            else:
                seg_ctx = None
                scope = contextlib.nullcontext()
            this_resume = resume
            devs = devices
            try:
                with checkpoint_clock_scope(clock), scope:
                    result = sup.call(
                        lambda: self._optimize_on_device(
                            state, options, verbose=verbose, config=cfg,
                            devices=devs, resume=this_resume,
                        ),
                        op="optimize", breaker=brk, mesh_devices=devs,
                    )
            except DeviceDegradedError as e:
                ft.note_width_result(width, ok=False)
                if seg_ctx is not None:
                    # the last offered snapshot may still be persisting on
                    # the background thread — land it before reading the
                    # slot, or a fast failure resumes one boundary stale
                    seg_ctx.wait_snapshot()
                    ft.note_checkpoint_seconds(seg_ctx.snapshot_seconds)
                if e.failure_class not in MESH_FAILURE_CLASSES:
                    # not attributable to specific chips: today's behavior
                    return self._optimize_degraded(
                        state, options, cfg,
                        reason=e.failure_class.value, cause=e,
                    )
                suspects = tuple(int(d) for d in (e.device_ids or ()))
                lost.extend(suspects)
                failed_ids = [int(d.id) for d in devices]
                self._purge_parallel_for_mesh_failure(suspects, failed_ids)
                survivors = (
                    [d for d in devices if int(d.id) not in set(suspects)]
                    if suspects
                    else devices
                )
                nxt = self._reduced_mesh_devices(survivors, below=width)
                ft.note_degrade(
                    lost=suspects,
                    from_width=width,
                    to_width=len(nxt) if nxt is not None else 1,
                    failure_class=e.failure_class.value,
                )
                resume = slot.latest()
                last_mesh_error = e
                devices = nxt
                continue
            ft.note_width_result(width, ok=True)
            if seg_ctx is not None:
                seg_ctx.wait_snapshot()
                ft.note_checkpoint_seconds(seg_ctx.snapshot_seconds)
            ft.note_run_completed(
                width=width, full_width=full_width,
                resumed=this_resume is not None,
            )
            if lost or width < full_width:
                # stamp the degrade on the result: consumers (bench gate,
                # /explain) see which chips were lost and whether the
                # anneal RESUMED (vs restarted) without digging sensors
                result.history.append(
                    dict(
                        mesh_ft=True,
                        lost_devices=sorted(set(lost)),
                        width=width,
                        full_width=full_width,
                        resumed=this_resume is not None,
                        resumed_from_round=(
                            int(this_resume.base)
                            if this_resume is not None
                            else None
                        ),
                    )
                )
            return result
        # no mesh width survives: plain engine under the single-device
        # breaker, then the CPU greedy floor
        from cruise_control_tpu.common.device_watchdog import DeviceDegradedError

        if not sup.available():
            return self._optimize_degraded(
                state, options, cfg, reason="breaker-open",
                cause=last_mesh_error,
            )
        try:
            return sup.call(
                lambda: self._optimize_on_device(
                    state, options, verbose=verbose, config=cfg,
                    force_single=True,
                ),
                op="optimize",
            )
        except DeviceDegradedError as e:
            self._maybe_purge_after_open()
            return self._optimize_degraded(
                state, options, cfg,
                reason=e.failure_class.value, cause=e,
            )

    def optimize_streaming_cycle(
        self,
        state: ClusterState,
        *,
        rows,
        leader_loads,
        follower_loads,
        initial_placement,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig | None = None,
        prior=None,
        before_host: dict | None = None,
    ):
        """The steady-state streaming cycle as ONE device dispatch + ONE
        host extraction (Engine.run_cycle): delta scatter, warm re-anneal,
        before/after reports, device validation, and the proposal payload
        all ride a single donated jitted program.

        Returns `(OptimizerResult, (new_ll, new_fl))` — the donated-and-
        rescattered live load arrays the caller MUST adopt as the new
        live state (`state`'s own load leaves are dead after this call) —
        or None when the fast path is unavailable: non-single parallel
        mode, supervisor breaker open, or no cached engine for
        (state.shape, config).  On None the caller falls back to the
        staged scatter+optimize path, which builds and caches the engine
        so the NEXT cycle goes fused.

        `before_host` is the controller's reflatten-time placement cache
        (fetch_before_host of the flattened state): placement columns are
        delta-invariant between reflattens, so only `replica_disk_bytes`
        — which the scatter just changed — is refreshed, from the cycle
        payload, with zero extra device traffic.

        The engine call is NOT supervisor-wrapped: supervision exists to
        classify compile hangs and device faults into a degraded answer,
        but the cycle requires an already-cached engine whose programs
        the staged path (which IS supervised) compiled; a post-donation
        failure here must propagate anyway — the live load buffers were
        consumed, so only a reflatten can recover, and the controller's
        loop-failure accounting owns that."""
        cfg = config or self.config
        if self.parallel_mode != "single":
            return None
        sup = self.supervisor
        if sup is not None and not sup.available():
            return None
        engine = self._cache_get(self._engines, (state.shape, cfg))
        if engine is None:
            return None
        from cruise_control_tpu.models.state import DEVICE_CHECKS

        t0 = time.monotonic()
        with self.tracer.span("analyzer.optimize", component="analyzer") as sp:
            try:
                # data-only statics refresh: the prior's CDF/mix are the
                # only statics fields a delta cycle changes (placement
                # metadata is reflatten-invariant; loads are scattered
                # in-graph)
                engine.rebind_prior(prior)
                out_ll, out_fl, host, history = engine.run_cycle(
                    state.replica_load_leader,
                    state.replica_load_follower,
                    rows, leader_loads, follower_loads,
                    initial_placement,
                )
            finally:
                self._unpin(engine)
            self._record(True)
            checks = np.asarray(host["checks"])
            if checks.any():
                bad = [n for n, c in zip(DEVICE_CHECKS, checks) if c]
                raise ValueError(f"optimized state failed sanity checks: {bad}")
            # the effective BEFORE state: the live state with the freshly
            # scattered loads (what the staged path's scatter would have
            # produced); AFTER adds the payload's host placement arrays
            state_before = dataclasses.replace(
                state,
                replica_load_leader=out_ll,
                replica_load_follower=out_fl,
            )
            state_after = dataclasses.replace(
                state_before,
                replica_broker=host["replica_broker"],
                replica_is_leader=host["replica_is_leader"],
                replica_disk=host["replica_disk"],
                replica_offline=host["replica_offline"],
            )
            t_extract = time.monotonic()
            if before_host is not None:
                before_host = dict(
                    before_host, replica_disk_bytes=host["replica_disk_bytes"]
                )
            else:
                from cruise_control_tpu.analyzer.proposals import fetch_before_host

                before_host = fetch_before_host(state_before)
            proposals = extract_proposals(
                state_before, state_after, before_host=before_host
            )
            timing = next((h for h in history if h.get("timing")), None)
            if timing is None:
                timing = dict(timing=True)
                history.append(timing)
            timing["host_extract_s"] = round(time.monotonic() - t_extract, 6)
            timing["engine_cache_hit"] = True
            timing["engine_build_s"] = 0.0
            s = state.shape
            timing["bucket"] = dict(R=s.R, B=s.B, P=s.P, T=s.num_topics)
            viol_b = np.asarray(host["viol_before"])
            viol_a = np.asarray(host["viol_after"])
            wall = time.monotonic() - t0
            result = OptimizerResult(
                proposals=proposals,
                state_before=state_before,
                state_after=state_after,
                stats_before=host["stats_before"],
                stats_after=host["stats_after"],
                goal_names=self.chain.names(),
                violations_before=viol_b,
                violations_after=viol_a,
                balancedness_before=balancedness_score(
                    viol_b,
                    self.chain,
                    priority_weight=self.balancedness_weights[0],
                    strictness_weight=self.balancedness_weights[1],
                ),
                balancedness_after=balancedness_score(
                    viol_a,
                    self.chain,
                    priority_weight=self.balancedness_weights[0],
                    strictness_weight=self.balancedness_weights[1],
                ),
                objective_before=float(host["obj_before"]),
                objective_after=float(host["obj_after"]),
                wall_seconds=wall,
                history=history,
            )
            sp.set(
                parallel_mode=self.parallel_mode,
                fused_cycle=True,
                degraded=False,
                wall_s=round(wall, 6),
                num_proposals=len(result.proposals),
                objective_after=round(result.objective_after, 6),
                balancedness_after=round(result.balancedness_after, 3),
                **{
                    k: timing.get(k)
                    for k in (
                        "device_s", "blocking_syncs", "host_extract_s",
                        "scatter_width", "bucket", "convergence",
                    )
                    if timing.get(k) is not None
                },
            )
            return result, (out_ll, out_fl)

    # ------------------------------------------------------------------
    # per-bucket compile-time attribution (device profiling surface)
    # ------------------------------------------------------------------

    @staticmethod
    def _bucket_key(shape) -> str:
        # one definition (analyzer/prewarm.py): compile attribution, the
        # boot-prewarm manifest, and the coldstart bench's trace report
        # must all name a bucket the same way
        from cruise_control_tpu.analyzer.prewarm import bucket_key

        return bucket_key(shape)

    def _attribute_cold_run(self, shape, *, wall_s: float, build_s: float) -> None:
        with self._cache_lock:
            row = self._compile_attribution.setdefault(
                self._bucket_key(shape),
                {"compiles": 0, "coldWallSeconds": 0.0, "buildSeconds": 0.0},
            )
            row["compiles"] += 1
            row["coldWallSeconds"] = round(row["coldWallSeconds"] + wall_s, 6)
            row["buildSeconds"] = round(row["buildSeconds"] + build_s, 6)

    def compile_attribution(self) -> dict[str, dict]:
        """Cumulative cold-start bill per shape bucket.  A cache-miss
        run's wall INCLUDES its lazy XLA compile (engine_build_s is host
        construction only), so coldWallSeconds is the honest per-bucket
        compile+first-run cost — what ROADMAP item 2's persistent compile
        cache must drive toward zero.  /state AnalyzerState carries it;
        the `analyzer.engine-compile-seconds-by-bucket` collector exposes
        it to /metrics."""
        with self._cache_lock:
            return {k: dict(v) for k, v in self._compile_attribution.items()}

    def compile_attribution_values(self) -> list[tuple[dict, float]]:
        """Collector callback: [({"bucket": key}, coldWallSeconds), ...]."""
        return [
            ({"bucket": k}, v["coldWallSeconds"])
            for k, v in self.compile_attribution().items()
        ]

    def _maybe_purge_after_open(self) -> None:
        """Drop cached engines once per breaker-open transition: a device
        that just wedged/OOMed owns buffers of unknown integrity, and
        recovery should rebuild engines fresh rather than rebind onto
        them.  SCOPED to the failing parallel mode: the single-device
        breaker guards the plain-engine path, so its open drops only
        `_engines` — mesh engines have their own per-width breakers and
        are purged at THEIR failure site (_purge_parallel_for_mesh_failure)
        — except when mesh ft is off and mesh dispatches still ride this
        breaker.  Pinned engines (a hung run still references one from its
        abandoned thread) are dropped from the cache but left to GC."""
        sup = self.supervisor
        if sup is None or sup.open_epoch == self._breaker_epoch:
            return
        self._breaker_epoch = sup.open_epoch
        caches = [self._engines]
        ft = self._mesh_ft
        if self.parallel_mode != "single" and (ft is None or not ft.enabled):
            caches.append(self._parallel_engines)
        released = []
        with self._cache_lock:
            for cache in caches:
                released.extend(cache.values())
                cache.clear()
        for e in released:
            if not getattr(e, "_cc_busy", 0):
                _release_engine(e)
        self._record(False, count=False)  # refresh the size gauge

    def _optimize_on_device(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        *,
        verbose: bool = False,
        config: OptimizerConfig | None = None,
        initial_placement=None,
        prior=None,
        devices=None,
        resume=None,
        force_single: bool = False,
    ) -> OptimizerResult:
        """`devices` / `resume` / `force_single` are the mesh
        fault-tolerance ladder's knobs (_optimize_mesh_ft): build the mesh
        engine over a survivor subset, continue a checkpointed anneal from
        its last slice boundary, or take the plain-engine rung below the
        mesh.  All three default to today's behavior."""
        from concurrent.futures import ThreadPoolExecutor

        from cruise_control_tpu.analyzer.proposals import fetch_before_host
        from cruise_control_tpu.models.state import DEVICE_CHECKS, validate_on_device

        t0 = time.monotonic()
        cfg = config or self.config
        # input sanity first — a rejected state must not trigger engine
        # construction or background compilation.  The ON-DEVICE check
        # transfers a [5] count vector instead of the model's bulk arrays
        # (the tunneled-TPU transfer costs more than the checks); the host
        # validator re-runs for the detailed message only on failure
        count_dispatch("analyzer.validate")
        input_checks = np.asarray(validate_on_device(state))
        if input_checks.any():
            validate(state)  # raises with per-invariant detail
            bad = [n for n, c in zip(DEVICE_CHECKS, input_checks) if c]
            raise ValueError(f"input state failed sanity checks: {bad}")
        # build + warm the engine BEFORE the report: program tracing/
        # compiling proceeds on background threads while the main thread
        # traces the report programs below — the restarted-service warm
        # start (engine.precompile_async docstring)
        engine = None
        cache_info = None
        single = self.parallel_mode == "single" or force_single
        try:
            if single:
                engine, cache_info = self._engine_for(
                    state, options, cfg, prior=prior
                )
            else:
                if initial_placement is not None or prior is not None:
                    raise ValueError(
                        "warm-start placement / move-acceptance prior are "
                        f"single-device only (tpu.parallel.mode={self.parallel_mode!r})"
                    )
                engine, cache_info = self._parallel_engine(
                    state, options, cfg, devices=devices
                )
            # only at production scale: tiny test engines compile in
            # hundreds of ms, and eagerly tracing the rarely-used
            # programs (full-chain violations) would cost more than
            # the overlap wins.  Plain and mesh engines warm through the
            # SAME pool (engine.start_warm_pool), so the sharded variants'
            # shard_map tracing overlaps the report tracing below exactly
            # like the single-device warm start.  An AOT-worthy engine
            # under a bound prewarm store also warms: the warm pool is
            # where artifacts are loaded/exported, and the restart SLO
            # depends on that happening for every active bucket that
            # would pay a real tracing bill.
            aot_worthy = getattr(engine, "aot_worthwhile", None)
            if (
                state.shape.R >= 65_536
                or cfg.num_candidates >= 8_192
                or (
                    self.prewarm_store is not None
                    and aot_worthy is not None
                    and aot_worthy()
                )
            ):
                engine.precompile_async()
            count_dispatch("analyzer.report")
            (obj_b, viol_b), stats_b = self._report(state)
            # the proposal diff needs bulk BEFORE-state arrays on host;
            # pull them on a side thread while the device anneals — input
            # buffers are immutable, and the copy rides the link during
            # compute the host would otherwise spend blocked on the engine
            with ThreadPoolExecutor(max_workers=1) as pool:
                before_host_f = pool.submit(fetch_before_host, state)
                # opt-in device profiling (config tpu.profiler.*): the
                # engine run — where the XLA program actually executes —
                # is the block a profiler dump illuminates
                from cruise_control_tpu.common.profiling import profiler_trace

                run_kwargs = (
                    {"initial_placement": initial_placement}
                    if initial_placement is not None
                    else {}
                )
                if resume is not None and not single:
                    if getattr(engine, "model_sharded", False):
                        # the sharded-model mode has no segmented variant
                        # (mesh.py run() docstring): a reduced-width
                        # retry restarts the schedule instead of resuming
                        resume = None
                    else:
                        run_kwargs["resume"] = resume
                with profiler_trace(self.profiler_dir):
                    final, history = engine.run(verbose=verbose, **run_kwargs)
                before_host = before_host_f.result()
        finally:
            # run() is done with the engine's buffers (everything below
            # reads only the run's OUTPUT arrays); release the eviction
            # pin on EVERY exit path — a pin leaked on an exception would
            # exempt the engine from hard release forever
            if engine is not None:
                self._unpin(engine)
        # dispatch the result report + the on-device sanity check, then do
        # the host-side proposal diff while the device drains them
        count_dispatch("analyzer.report")
        (obj_a, viol_a), stats_a = self._report(final)
        count_dispatch("analyzer.validate")
        final_checks = validate_on_device(final)
        t_extract = time.monotonic()
        proposals = extract_proposals(state, final, before_host=before_host)
        extract_s = time.monotonic() - t_extract
        # complete the device/host timing split the engine started: the
        # proposal diff is the optimizer's host-side share of the wall
        # clock, overlapping the device draining the report programs above
        timing = next((h for h in history if h.get("timing")), None)
        if timing is None:
            timing = dict(timing=True)
            history.append(timing)
        timing["host_extract_s"] = round(extract_s, 6)
        # compile-vs-rebind outcome + the (bucketed) shape served: the
        # observable proof that shape bucketing absorbed a topology change
        # (engine_cache_hit=True, compile_s ~ rebind cost) vs paid a compile
        if cache_info is not None:
            timing.update(cache_info)
        s = state.shape
        timing["bucket"] = dict(R=s.R, B=s.B, P=s.P, T=s.num_topics)
        if self.peak_tracker is not None:
            self.peak_tracker.record(f"R{s.R}-B{s.B}-P{s.P}")
        if self.sensors is not None and timing.get("mesh_shape"):
            # mesh-engine observability (docs/sensors.md "analyzer.mesh-*"):
            # shard count and per-round collective payload are the two
            # numbers that decide whether cross-shard overhead is paying off
            self.sensors.counter("analyzer.mesh-runs").inc()
            self.sensors.gauge("analyzer.mesh-shards").set(
                int(timing["mesh_shape"][1])
            )
            self.sensors.gauge("analyzer.mesh-collective-bytes").set(
                int(timing.get("collective_bytes") or 0)
            )
            if timing.get("model_sharded"):
                # sharded-MODEL runs: the psum payload replacing the
                # replicated model's gathers is the cost side of the
                # ~1/n per-chip memory win (parallel/model_shard.py)
                self.sensors.counter("analyzer.mesh-model-sharded-runs").inc()
                self.sensors.gauge("analyzer.mesh-model-psum-bytes").set(
                    int(timing.get("model_psum_bytes") or 0)
                )
        final_checks = np.asarray(final_checks)
        if final_checks.any():
            bad = [n for n, c in zip(DEVICE_CHECKS, final_checks) if c]
            # re-run the host validator for the detailed message
            validate(final)
            raise ValueError(f"optimized state failed sanity checks: {bad}")
        viol_b = np.asarray(viol_b)
        viol_a = np.asarray(viol_a)
        wall = time.monotonic() - t0
        if cache_info is not None and not cache_info.get("engine_cache_hit", True):
            # cold run: the whole wall (incl. the lazy XLA compile) bills
            # to this shape bucket's cold-start attribution
            self._attribute_cold_run(
                state.shape,
                wall_s=wall,
                build_s=cache_info.get("engine_build_s", 0.0),
            )
        return OptimizerResult(
            proposals=proposals,
            state_before=state,
            state_after=final,
            stats_before=stats_b,
            stats_after=stats_a,
            goal_names=self.chain.names(),
            violations_before=viol_b,
            violations_after=viol_a,
            balancedness_before=balancedness_score(
                viol_b,
                self.chain,
                priority_weight=self.balancedness_weights[0],
                strictness_weight=self.balancedness_weights[1],
            ),
            balancedness_after=balancedness_score(
                viol_a,
                self.chain,
                priority_weight=self.balancedness_weights[0],
                strictness_weight=self.balancedness_weights[1],
            ),
            objective_before=float(obj_b),
            objective_after=float(obj_a),
            wall_seconds=wall,
            history=history,
        )

    # ------------------------------------------------------------------
    # degraded mode (CPU greedy fallback under an open breaker)
    # ------------------------------------------------------------------

    def _optimize_degraded(
        self,
        state: ClusterState,
        options: OptimizationOptions,
        cfg: OptimizerConfig,
        *,
        reason: str,
        cause=None,
    ) -> OptimizerResult:
        """Serve a proposal set WITHOUT the accelerator: the CPU greedy
        oracle (analyzer/greedy.py) under a wall-clock budget, with the
        report programs pinned to the host CPU backend.

        The result is a real OptimizerResult — same extraction semantics,
        same stats/violations/balancedness surface — tagged with a
        `degraded` history record so callers (and the /state endpoint) can
        tell a greedy answer from a TPU answer.  Model arrays are pulled
        to host first; a model already materialized on a wedged device
        cannot be rescued here (the monitor rebuilds from host-side
        samples on the next generation), which is why the facade's model
        build path keeps host copies of every churn-prone array.
        """
        import jax

        from cruise_control_tpu.analyzer.greedy import greedy_optimize
        from cruise_control_tpu.analyzer.proposals import extract_proposals as _extract

        t0 = time.monotonic()
        cpu = jax.local_devices(backend="cpu")[0]
        host_state = jax.tree.map(np.asarray, state)
        # same input contract as the device path: a rejected state raises
        # with per-invariant detail instead of being greedily "optimized"
        validate(host_state)
        final, info = greedy_optimize(
            host_state,
            self.chain,
            self.constraint,
            seed=cfg.seed,
            time_budget_s=self.degraded_budget_s,
            return_info=True,
            device=cpu,
            options=options,  # degraded fixes keep their exclusion contract
        )
        final = jax.tree.map(np.asarray, final)
        if self._report_cpu is None:
            self._report_cpu = jax.jit(
                lambda s: (
                    self.chain.evaluate(s, constraint=self.constraint)[:2],
                    compute_stats(s),
                )
            )
        with jax.default_device(cpu):
            (obj_b, viol_b), stats_b = self._report_cpu(host_state)
            (obj_a, viol_a), stats_a = self._report_cpu(final)
        t_extract = time.monotonic()
        proposals = _extract(host_state, final)
        s = host_state.shape
        history = [
            dict(
                timing=True,
                degraded=True,
                reason=reason,
                failure=(repr(cause) if cause is not None else None),
                greedy=info,
                host_extract_s=round(time.monotonic() - t_extract, 6),
                bucket=dict(R=s.R, B=s.B, P=s.P, T=s.num_topics),
            )
        ]
        if self.sensors is not None:
            self.sensors.counter("analyzer.degraded-proposals").inc()
        viol_b = np.asarray(viol_b)
        viol_a = np.asarray(viol_a)
        return OptimizerResult(
            proposals=proposals,
            state_before=host_state,
            state_after=final,
            stats_before=stats_b,
            stats_after=stats_a,
            goal_names=self.chain.names(),
            violations_before=viol_b,
            violations_after=viol_a,
            balancedness_before=balancedness_score(
                viol_b,
                self.chain,
                priority_weight=self.balancedness_weights[0],
                strictness_weight=self.balancedness_weights[1],
            ),
            balancedness_after=balancedness_score(
                viol_a,
                self.chain,
                priority_weight=self.balancedness_weights[0],
                strictness_weight=self.balancedness_weights[1],
            ),
            objective_before=float(obj_b),
            objective_after=float(obj_a),
            wall_seconds=time.monotonic() - t0,
            history=history,
        )
