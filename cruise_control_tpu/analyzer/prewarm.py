"""Boot prewarm manifest + AOT-serialized engine programs.

The warm-up wall: steady-state device wall is ~6.5 s, but every process
restart pays 15-180 s of Python tracing + XLA compile before the first
proposal (BENCH_r03-r05) — the persistent XLA cache (PR 9,
common/compilation_cache.py) skips the compile but not the tracing, and
only once a proposal pass happens to request that bucket.  This module
closes both gaps:

  * **Manifest** (`PrewarmStore.note`): on every engine build/rebind the
    service records its ACTIVE working set — bucketed shape (+ max_rf,
    the one aval axis the shape alone does not pin), the full
    OptimizerConfig, parallel mode, and an environment fingerprint
    (jax/jaxlib version + goal chain + constraint) — to a small durable
    JSON file inside the compile cache's mount (config
    `tpu.prewarm.manifest.*`; the cache's inventory scan prunes it).
    Entries are MERGED on write (read-modify-write under the file's
    directory, dedup by bucket+config+fingerprint), so N fleet facades
    sharing one AnalyzerCore — or two processes sharing one cache
    directory — union their working sets instead of last-writer-wins.
    On boot, `CruiseControl.start_up()` replays the manifest through the
    warm pool (`claim_boot_entries` → `GoalOptimizer.prewarm`) so the
    active buckets are compiling BEFORE the first request, the recovery
    resume, or the streaming controller's first cycle needs a proposal.

  * **AOT artifacts** (`_AotHandle`): the fused whole-anneal program is
    exported per (bucket, config-fingerprint) via `jax.export` the first
    time it compiles, so a warm-disk restart skips Python tracing too.
    Done right this time (the round-4 in-line attempt regressed warm
    start and broke multi-device modes — see Engine.precompile_async):
    deserialization runs ONLY on the warm-pool workers, never the
    request path; artifacts are keyed strictly on the manifest
    fingerprint + the exact input avals + jax/jaxlib version + backend
    platform; and any drift or corruption makes `load` return None so
    the caller falls back to the plain-jit path — correctness never
    depends on an artifact.  The export step also compiles the exported
    module once (in the background, off the request path) so its XLA
    executable lands in the persistent compile cache: the next restart
    pays neither the trace nor the compile.

Reference analog: none — a JVM has no trace/compile step to amortize;
this is the TPU framework's restart SLO (ROADMAP item 2), gated by
`bench.py --coldstart`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import tempfile
import threading
import time

log = logging.getLogger(__name__)

#: manifest + artifact layout version; a bump invalidates old files
VERSION = 1

#: throttle for recency-only manifest rewrites (a rebind storm must not
#: turn into an fsync storm; new entries always write immediately)
_TOUCH_WRITE_INTERVAL_S = 60.0

_BUCKET_FIELDS = (
    "R", "B", "P", "topics", "racks", "hosts", "disks", "max_rf"
)


def bucket_key(shape) -> str:
    """Human-readable bucket id — the SAME format GoalOptimizer's
    compile attribution uses, so boot reports and /state rows join."""
    return f"R{shape.R}.B{shape.B}.P{shape.P}.T{shape.num_topics}"


def _bucket_dict(shape, max_rf: int) -> dict:
    return {
        "R": int(shape.num_replicas),
        "B": int(shape.num_brokers),
        "P": int(shape.num_partitions),
        "topics": int(shape.num_topics),
        "racks": int(shape.num_racks),
        "hosts": int(shape.num_hosts),
        "disks": int(shape.max_disks_per_broker),
        "max_rf": int(max_rf),
    }


def _shape_from_dict(b: dict):
    from cruise_control_tpu.models.state import ClusterShape

    return ClusterShape(
        num_replicas=int(b["R"]),
        num_brokers=int(b["B"]),
        num_partitions=int(b["P"]),
        num_topics=int(b["topics"]),
        num_racks=int(b["racks"]),
        num_hosts=int(b["hosts"]),
        max_disks_per_broker=int(b["disks"]),
    )


def _sha(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:16]


_source_digest_cache: str | None = None


def _source_digest() -> str:
    """Digest of the Python source that DEFINES the traced engine
    programs (analyzer/ + models/).  An AOT artifact is a frozen trace:
    without this, editing the engine's math would keep serving the OLD
    program from a shared artifact directory — silently.  The persistent
    XLA cache is immune (keyed by HLO); the artifact tier must key on
    source identity explicitly."""
    global _source_digest_cache
    if _source_digest_cache is not None:
        return _source_digest_cache
    h = hashlib.sha256()
    try:
        import cruise_control_tpu.analyzer as _ana
        import cruise_control_tpu.models as _mod

        for pkg in (_ana, _mod):
            root = os.path.dirname(os.path.abspath(pkg.__file__))
            for dirpath, dirs, files in os.walk(root):
                dirs.sort()  # readdir order is filesystem-dependent: two
                # hosts sharing one artifact dir must digest identically
                for fn in sorted(files):
                    if not fn.endswith(".py"):
                        continue
                    path = os.path.join(dirpath, fn)
                    with open(path, "rb") as f:
                        # relative path + separator: a file moved between
                        # subpackages (or renamed) must change the digest
                        h.update(os.path.relpath(path, root).encode() + b"\0")
                        h.update(f.read())
    except Exception:  # noqa: BLE001 — source unavailable (frozen install):
        # fall back to version-only keying rather than disabling prewarm
        h.update(b"no-source")
    _source_digest_cache = h.hexdigest()[:16]
    return _source_digest_cache


def environment_fingerprint(chain, constraint) -> str:
    """Strict identity of everything an engine program bakes in BESIDES
    the OptimizerConfig (which rides each entry verbatim so it can be
    reconstructed): goal chain (names + weights), constraint thresholds,
    the jax/jaxlib versions, and a digest of the engine/model source
    itself (an artifact is a frozen trace — a code change must
    invalidate it).  A restart under a different chain, thresholds,
    runtime, or code must not prewarm (or deserialize) stale programs —
    mismatched entries are simply skipped."""
    import jax
    import jaxlib

    names = ",".join(g.name for g in chain.goals)
    weights = ",".join(repr(float(w)) for w in chain.weights)
    return _sha(
        f"v{VERSION}|{jax.__version__}|{jaxlib.__version__}"
        f"|{_source_digest()}|{names}|{weights}|{constraint!r}"
    )


def _config_dict(config) -> dict:
    return dataclasses.asdict(config)


def _config_from_dict(d: dict):
    """OptimizerConfig back from its JSON form; raises on unknown fields
    (a manifest written by a future version must be skipped, not
    half-applied)."""
    from cruise_control_tpu.analyzer.engine import OptimizerConfig

    return OptimizerConfig(**d)


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-", suffix=os.path.basename(path))
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class _AotHandle:
    """Load/save seam for ONE fused program's AOT artifact.

    `load` runs on a warm-pool worker and returns a COMPILED flat
    executable, or None on any mismatch (version, fingerprint, platform,
    avals, checksum) or corruption — the caller's fresh-compile path is
    always the fallback.  `save` exports + persists + compiles the
    exported module once so the persistent XLA cache holds its
    executable for the next restart."""

    def __init__(self, store: "PrewarmStore", key_fp: str, bucket: str):
        self.store = store
        self.key_fp = key_fp
        self.bucket = bucket

    @property
    def path(self) -> str:
        return os.path.join(self.store.directory, f"fused-{self.key_fp}.aot")

    # -------------------------------------------------------------- load

    def load(self, leaves_avals, donate_argnums):
        """Deserialize + compile the artifact against the CURRENT avals.
        None on any problem; never raises."""
        import jax

        self.store.aot_load_attempts += 1
        try:
            with open(self.path, "rb") as f:
                header_line = f.readline()
                payload = f.read()
        except OSError:
            return None  # no artifact: the ordinary cold path
        try:
            header = json.loads(header_line)
            if header.get("v") != VERSION:
                raise ValueError(f"artifact version {header.get('v')}")
            import jaxlib

            if (
                header.get("jax") != jax.__version__
                or header.get("jaxlib") != jaxlib.__version__
            ):
                raise ValueError("jax/jaxlib version drift")
            if header.get("fp") != self.key_fp:
                raise ValueError("fingerprint mismatch")
            if header.get("platform") != jax.default_backend():
                raise ValueError(
                    f"platform {header.get('platform')} != {jax.default_backend()}"
                )
            if header.get("sha256") != hashlib.sha256(payload).hexdigest():
                raise ValueError("payload checksum mismatch (corrupt/truncated)")
            want = [[list(a.shape), str(a.dtype)] for a in leaves_avals]
            if header.get("avals") != want:
                raise ValueError("input aval drift")
            from jax import export as jax_export

            ex = jax_export.deserialize(payload)
            compiled = (
                jax.jit(ex.call, donate_argnums=tuple(donate_argnums))
                .trace(*leaves_avals)
                .lower()
                .compile()
            )
        except Exception as e:  # noqa: BLE001 — artifact is an optimization only
            self.store._count("analyzer.prewarm-aot-rejects")
            log.warning("AOT artifact %s rejected: %r", self.path, e)
            # a rejected artifact must not poison its bucket forever:
            # save_async skips existing files, so leaving the bad one in
            # place would disable the AOT tier for this bucket on every
            # future restart — delete it and let the fresh path re-export
            try:
                os.unlink(self.path)
            except OSError:
                pass
            return None
        self.store._count("analyzer.prewarm-aot-hits")
        return compiled

    # -------------------------------------------------------------- save

    def save_async(self, flat_fn, leaves_avals, donate_argnums, *, priority=1_000):
        """Schedule export+persist (+ one compile of the exported module,
        seeding the persistent XLA cache) on the warm pool at LOW
        priority — never on the path that is waiting for a compile."""
        if os.path.exists(self.path):
            return None
        from cruise_control_tpu.analyzer.engine import warm_pool_submit

        fut = warm_pool_submit(
            lambda: self._save(flat_fn, leaves_avals, donate_argnums),
            priority=priority,
        )
        with self.store._lock:
            self.store._export_futures.append(fut)
        return fut

    def _save(self, flat_fn, leaves_avals, donate_argnums) -> str:
        import jax
        import jaxlib
        from jax import export as jax_export

        jitted = jax.jit(flat_fn, donate_argnums=tuple(donate_argnums))
        ex = jax_export.export(jitted)(*leaves_avals)
        payload = ex.serialize()
        header = {
            "v": VERSION,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "platform": jax.default_backend(),
            "fp": self.key_fp,
            "bucket": self.bucket,
            "avals": [[list(a.shape), str(a.dtype)] for a in leaves_avals],
            "sha256": hashlib.sha256(payload).hexdigest(),
            "ms": int(time.time() * 1000),
        }
        _atomic_write(
            self.path, json.dumps(header).encode() + b"\n" + payload
        )
        # compile the EXPORTED module once so its executable is in the
        # persistent XLA cache: a restart's deserialize-then-compile is a
        # disk hit, not a fresh compile.  (The exported module is not
        # byte-identical to the plain jit's, so without this the first
        # AOT boot would pay the compile the cache was supposed to skip.)
        jax.jit(ex.call, donate_argnums=tuple(donate_argnums)).trace(
            *leaves_avals
        ).lower().compile()
        self.store._count("analyzer.prewarm-aot-exports")
        return self.path


class PrewarmStore:
    """One durable manifest (+ AOT artifact directory) per deployment.

    Built by AnalyzerCore from `tpu.prewarm.*` config and shared by every
    facade over that core (the fleet's merge-not-clobber requirement);
    handed to the long-lived GoalOptimizer only — ad-hoc per-request
    optimizers (custom goal lists) are transient and never recorded."""

    def __init__(
        self,
        directory: str,
        *,
        chain,
        constraint,
        aot_enabled: bool = True,
        max_entries: int = 6,
        sensors=None,
    ):
        self.directory = os.path.expanduser(directory)
        self.env_fp = environment_fingerprint(chain, constraint)
        self.aot_enabled = aot_enabled
        self.max_entries = max(1, int(max_entries))
        self.sensors = sensors
        self._lock = threading.Lock()
        #: in-memory view of OUR entries, key -> entry dict
        self._entries: dict[str, dict] = {}
        self._last_write = 0.0
        self._boot_claimed = False
        self._export_futures: list = []
        #: observability for the never-on-the-request-path guard
        self.aot_load_attempts = 0

    # ------------------------------------------------------------ sensors

    def _count(self, name: str) -> None:
        if self.sensors is not None:
            try:
                self.sensors.counter(name).inc()
            except Exception:  # noqa: BLE001 — accounting must never raise
                pass

    # ------------------------------------------------------------- paths

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, "prewarm-manifest.json")

    # ----------------------------------------------------------- editing

    @staticmethod
    def _entry_key(entry: dict) -> str:
        ident = json.dumps(
            [
                entry["env_fp"],
                [entry["bucket"][f] for f in _BUCKET_FIELDS],
                sorted(entry["config"].items()),
                entry["parallel_mode"],
            ],
            default=str,
        )
        return _sha(ident)

    def note(self, shape, max_rf: int, config, *, parallel_mode: str = "single") -> None:
        """Record one (bucket, config) as active; merge + persist.

        Called on every engine build/rebind the long-lived optimizer
        performs.  New entries write through immediately; recency-only
        touches are throttled to one disk write per minute."""
        entry = {
            "env_fp": self.env_fp,
            "bucket": _bucket_dict(shape, max_rf),
            "config": _config_dict(config),
            "parallel_mode": str(parallel_mode),
            "last_used_ms": int(time.time() * 1000),
            "uses": 1,
        }
        key = self._entry_key(entry)
        with self._lock:
            known = key in self._entries
            if known:
                old = self._entries[key]
                entry["uses"] = int(old.get("uses", 0)) + 1
            self._entries[key] = entry
            now = time.monotonic()
            if known and now - self._last_write < _TOUCH_WRITE_INTERVAL_S:
                return
            self._last_write = now
            try:
                self._write_merged_locked()
            except Exception:  # noqa: BLE001 — the manifest is best-effort
                log.warning("prewarm manifest write failed", exc_info=True)

    def _read_file(self) -> dict[str, dict]:
        try:
            with open(self.manifest_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        if doc.get("version") != VERSION:
            return {}
        out = {}
        for e in doc.get("entries", ()):
            try:
                out[self._entry_key(e)] = e
            except Exception:  # noqa: BLE001 — one bad row must not poison the rest
                continue
        return out

    def _write_merged_locked(self) -> None:
        """Merge our in-memory entries over the on-disk file (another
        process — or another core over the same cache dir — may have
        written since) and persist atomically, bounded by max_entries in
        most-recently-used order.

        The read-modify-write is guarded by an OS file lock (flock on a
        sibling .lock file) so two PROCESSES cannot interleave their
        read and replace steps and silently drop each other's entries —
        self._lock only serializes threads of this store.  Writes are
        rare (new entries + throttled touches) and fast, so a blocking
        lock is fine; a platform without flock degrades to the unlocked
        (atomic-replace, last-merger-wins) behavior."""
        os.makedirs(self.directory, exist_ok=True)
        lock_f = None
        try:
            try:
                import fcntl

                lock_f = open(self.manifest_path + ".lock", "a")
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_EX)
            except Exception:  # noqa: BLE001 — no flock: best-effort merge
                lock_f = None
            merged = self._read_file()
            for k, e in self._entries.items():
                old = merged.get(k)
                if old is not None:
                    e = dict(e)
                    e["uses"] = max(int(e.get("uses", 1)), int(old.get("uses", 1)))
                    e["last_used_ms"] = max(
                        int(e.get("last_used_ms", 0)),
                        int(old.get("last_used_ms", 0)),
                    )
                merged[k] = e
            rows = sorted(
                merged.values(), key=lambda e: -int(e.get("last_used_ms", 0))
            )[: self.max_entries]
            _atomic_write(
                self.manifest_path,
                json.dumps(
                    {"version": VERSION, "entries": rows}, indent=1
                ).encode(),
            )
        finally:
            if lock_f is not None:
                lock_f.close()  # releases the flock

    # -------------------------------------------------------------- boot

    def claim_boot_entries(self) -> list[dict]:
        """The manifest's entries for THIS environment, most recent
        first (the ACTIVE bucket leads, so it compiles before any
        speculation) — claimed at most once per store so N fleet facades
        sharing one core run ONE boot prewarm between them."""
        with self._lock:
            if self._boot_claimed:
                return []
            self._boot_claimed = True
        rows = [
            e
            for e in self._read_file().values()
            if e.get("env_fp") == self.env_fp
        ]
        rows.sort(key=lambda e: -int(e.get("last_used_ms", 0)))
        return rows[: self.max_entries]

    @staticmethod
    def entry_engine_inputs(entry: dict):
        """(ClusterShape, max_rf, OptimizerConfig, parallel_mode) from a
        manifest row; raises on malformed/foreign rows (caller skips)."""
        shape = _shape_from_dict(entry["bucket"])
        return (
            shape,
            int(entry["bucket"]["max_rf"]),
            _config_from_dict(entry["config"]),
            str(entry["parallel_mode"]),
        )

    def manifest_bucket_keys(self) -> list[str]:
        """bucket_key() strings of on-disk entries for this environment
        (the cold-start bench's gate universe)."""
        return [
            bucket_key(_shape_from_dict(e["bucket"]))
            for e in self._read_file().values()
            if e.get("env_fp") == self.env_fp
        ]

    # --------------------------------------------------------------- aot

    def aot_handle(self, shape, max_rf: int, config) -> _AotHandle | None:
        """The artifact handle for one fused program, or None when AOT
        serialization is off.  The backend PLATFORM is part of the key:
        a CPU process and a TPU deployment sharing one artifact directory
        must keep separate artifacts, not alternately reject (and now
        delete) each other's."""
        if not self.aot_enabled:
            return None
        try:
            import jax

            platform = jax.default_backend()
        except Exception:  # noqa: BLE001 — backend unavailable: no AOT
            return None
        ident = json.dumps(
            [
                self.env_fp,
                platform,
                [_bucket_dict(shape, max_rf)[f] for f in _BUCKET_FIELDS],
                sorted(_config_dict(config).items()),
            ],
            default=str,
        )
        return _AotHandle(self, _sha(ident + "|aot"), bucket_key(shape))

    def drain(self, timeout_s: float = 120.0) -> bool:
        """Wait for pending AOT exports (bench/tests; a daemon-threaded
        export must not be lost to process exit mid-write).  True when
        everything finished in time."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            futs = list(self._export_futures)
        ok = True
        for f in futs:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # noqa: BLE001 — export failure is non-fatal
                ok = False
        return ok
