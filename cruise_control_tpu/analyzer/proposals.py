"""Execution proposals — the optimizer's output contract.

Reference: executor/ExecutionProposal.java:25 (old/new replica lists +
data-to-move) and analyzer/AnalyzerUtils.getDiff:50-117 (distribution diff
between pre- and post-optimization cluster models).  Here the diff is an
array comparison between two ClusterStates sharing the same replica axis.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.state import ClusterState


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment (reference executor/ExecutionProposal.java:25).

    Replica lists are broker ids, leader first (the reference keeps the new
    leader at the head of the new replica list).
    """

    partition: int
    topic: int
    old_leader: int
    new_leader: int
    old_replicas: tuple[int, ...]
    new_replicas: tuple[int, ...]
    #: per-replica (broker, old_disk, new_disk) intra-broker moves (JBOD)
    disk_moves: tuple[tuple[int, int, int], ...] = ()
    #: bytes of replica data crossing broker boundaries
    inter_broker_data_to_move: float = 0.0
    #: bytes of replica data moving between a broker's own logdirs
    intra_broker_data_to_move: float = 0.0

    @property
    def has_replica_action(self) -> bool:
        return set(self.old_replicas) != set(self.new_replicas)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader != self.new_leader

    def to_json(self) -> dict:
        return {
            "topicPartition": {"topic": int(self.topic), "partition": int(self.partition)},
            "oldLeader": int(self.old_leader),
            "oldReplicas": [int(b) for b in self.old_replicas],
            "newReplicas": [int(b) for b in self.new_replicas],
        }


BEFORE_HOST_KEYS = (
    "replica_valid", "replica_topic", "replica_broker", "replica_is_leader",
    "replica_disk", "replica_partition", "replica_pos",
)


def fetch_before_host(state: ClusterState) -> dict:
    """One batched device->host transfer of everything extract_proposals
    needs from the BEFORE state — on a tunneled TPU the transfer dominates,
    so callers fetch once and share.  Only the DISK column of the [R, 4]
    leader loads crosses (the full matrix would quadruple the payload)."""
    import jax

    from cruise_control_tpu.common.dispatch import count_dispatch

    count_dispatch("proposals.fetch")
    vals = jax.device_get(
        tuple(getattr(state, k) for k in BEFORE_HOST_KEYS)
        + (state.replica_load_leader[:, int(Resource.DISK)],)
    )
    out = dict(zip(BEFORE_HOST_KEYS, vals[:-1]))
    out["replica_disk_bytes"] = vals[-1]
    return out


class ProposalSet:
    """Columnar proposal set with LAZY ExecutionProposal materialization.

    The optimizer's native diff output is columnar (per-touched-partition
    numpy rows); building ~100k Python dataclass instances costs more than
    an entire device annealing round at north-star scale.  This sequence
    keeps the columns and materializes objects only when a consumer
    actually iterates (the executor at execution start, REST serializing
    its first-100 preview) — aggregate stats (move counts, data to move)
    come straight off the arrays.

    Quacks like the list the rest of the stack always consumed: len(),
    iteration, indexing/slicing, bool, list() all work.
    """

    def __init__(self, columns: dict, disk_rows: dict):
        self._c = columns
        self._disk_rows = disk_rows
        self._all: list[ExecutionProposal] | None = None

    # ---------------------------------------------------- aggregate stats

    def __len__(self) -> int:
        return len(self._c["touched"])

    def __bool__(self) -> bool:
        return len(self) > 0

    @property
    def num_inter_broker_moves(self) -> int:
        """Rows whose replica SET changed (ExecutionProposal.has_replica_action)."""
        return int(self._c["set_changed"].sum())

    @property
    def num_leadership_moves(self) -> int:
        c = self._c
        return int(((c["old_leader"] != c["new_leader"]) & ~c["set_changed"]).sum())

    @property
    def data_to_move(self) -> float:
        return float(self._c["data"].sum())

    @property
    def intra_data_to_move(self) -> float:
        return float(self._c["intra_data"].sum())

    @property
    def source_brokers(self) -> set[int]:
        """Brokers shipping replica data away (execution-ETA input)."""
        c = self._c
        src = c["tb_old"][c["moved"]]
        return {int(b) for b in np.unique(src)}

    def destination_pairs(self) -> tuple[np.ndarray, np.ndarray]:
        """(topic_id, destination_broker) pairs of replica MOVES — brokers
        receiving a replica of the partition they did not hold before.
        The observation unit of the learned move-acceptance prior
        (controller/prior.py); columnar, no object materialization."""
        c = self._c
        nb, ob = c["nb"], c["ob"]  # [N, max_rf], -1 pads
        incoming = (nb >= 0) & ~(nb[:, :, None] == ob[:, None, :]).any(-1)
        rows, cols = np.nonzero(incoming)
        return (
            c["topic"][rows].astype(np.int64),
            nb[rows, cols].astype(np.int64),
        )

    # ---------------------------------------------------- materialization

    def _rows(self, ks) -> list[ExecutionProposal]:
        c = self._c
        # the values tuple below is hand-ordered to match — this assert
        # makes a field reorder/insert in ExecutionProposal fail loudly
        # here instead of silently scrambling every proposal
        fields = tuple(f.name for f in dataclasses.fields(ExecutionProposal))
        assert fields == (
            "partition", "topic", "old_leader", "new_leader",
            "old_replicas", "new_replicas", "disk_moves",
            "inter_broker_data_to_move", "intra_broker_data_to_move",
        ), fields
        new = ExecutionProposal.__new__
        cls = ExecutionProposal
        disk_rows = self._disk_rows
        empty: tuple = ()
        out: list[ExecutionProposal] = []
        append = out.append
        for k, (p, t, olr, nlr, obk, nbk, nv, dt, idt) in zip(ks, zip(
            c["touched"][ks].tolist(), c["topic"][ks].tolist(),
            c["old_leader"][ks].tolist(), c["new_leader"][ks].tolist(),
            c["ob"][ks].tolist(), c["nb"][ks].tolist(),
            c["n_valid"][ks].tolist(), c["data"][ks].tolist(),
            c["intra_data"][ks].tolist(),
        )):
            o = new(cls)
            # frozen dataclass: populate __dict__ directly —
            # object.__setattr__ per field costs ~4x across ~100k proposals
            o.__dict__.update(zip(fields, (
                p, t, olr, nlr, tuple(obk[:nv]), tuple(nbk[:nv]),
                disk_rows.get(int(k), empty), dt, idt,
            )))
            append(o)
        return out

    def rows_at(self, indices) -> list[ExecutionProposal]:
        """Materialize ONLY the given rows (decision-ledger top-moves
        featurization: the top-N-by-data rows of a 100k-move plan must
        not force the whole set into Python objects)."""
        if self._all is not None:
            return [self._all[int(i)] for i in indices]
        return self._rows(np.asarray(indices, np.int64))

    def top_by_data(self, n: int) -> list[ExecutionProposal]:
        """The `n` proposals moving the most inter-broker data, selected
        on the columns (no materialization beyond the returned rows) —
        the decision ledger's top-moves accessor."""
        data = np.asarray(self._c["data"])
        return self.rows_at(np.argsort(-data)[: max(0, n)])

    def _materialize(self) -> list[ExecutionProposal]:
        if self._all is None:
            self._all = self._rows(np.arange(len(self)))
        return self._all

    def __iter__(self):
        return iter(self._materialize())

    def __getitem__(self, item):
        if isinstance(item, slice):
            if self._all is not None:
                return self._all[item]
            return self._rows(np.arange(len(self))[item])
        return self._materialize()[item]

    def __eq__(self, other):
        if isinstance(other, (list, tuple)):
            return self._materialize() == list(other)
        if isinstance(other, ProposalSet):
            return self._materialize() == other._materialize()
        return NotImplemented

    def __repr__(self) -> str:
        return f"ProposalSet({len(self)} proposals)"


def _empty_proposal_set() -> ProposalSet:
    z = np.zeros(0, np.int64)
    return ProposalSet(
        dict(touched=z, topic=z, old_leader=z, new_leader=z,
             ob=np.zeros((0, 1), np.int64), nb=np.zeros((0, 1), np.int64),
             n_valid=z, data=np.zeros(0), intra_data=np.zeros(0),
             set_changed=np.zeros(0, bool), moved=np.zeros((0, 1), bool),
             tb_old=np.zeros((0, 1), np.int64)),
        {},
    )


def extract_proposals(
    before: ClusterState,
    after: ClusterState,
    before_host: dict | None = None,
) -> ProposalSet:
    """Diff two placements into per-partition proposals
    (reference analyzer/AnalyzerUtils.getDiff:50-117).

    Vectorized over a padded [P, max_rf] partition-replica table: at
    LinkedIn scale a rebalance touches >100k partitions and per-partition
    numpy slicing would dominate the optimizer wall-clock.  Returns a
    columnar ProposalSet; ExecutionProposal objects materialize lazily.

    before_host: pre-fetched numpy copies of the before-state arrays
    (fetch_before_host) — skips re-transferring them.
    """
    import jax

    from cruise_control_tpu.analyzer.engine import partition_replica_table

    if before_host is None:
        before_host = fetch_before_host(before)
    valid = before_host["replica_valid"]
    topic = before_host["replica_topic"]
    b_old = before_host["replica_broker"]
    l_old = before_host["replica_is_leader"]
    d_old = before_host["replica_disk"]
    disk_bytes = before_host["replica_disk_bytes"]
    part_arr = before_host["replica_partition"]
    pos_arr = before_host["replica_pos"]
    # only the AFTER placement still lives on device — when the fused
    # cycle already delivered it as host arrays, device_get is a no-op
    # and no dispatch is charged
    if isinstance(after.replica_broker, jax.Array):
        from cruise_control_tpu.common.dispatch import count_dispatch

        count_dispatch("proposals.extract")
    b_new, l_new, d_new = jax.device_get((
        after.replica_broker, after.replica_is_leader, after.replica_disk,
    ))
    host = {
        "replica_valid": valid, "replica_partition": part_arr, "replica_pos": pos_arr,
    }

    changed = valid & ((b_old != b_new) | (l_old != l_new) | (d_old != d_new))
    if not changed.any():
        return _empty_proposal_set()
    touched = np.unique(part_arr[changed])

    # padded per-partition replica rows, already in preferred (pos) order
    table = partition_replica_table(before, host=host)[touched]  # [N, max_rf]
    R = before.shape.R
    mask = table < R  # [N, max_rf]
    rows = np.minimum(table, R - 1)

    tb_old = np.where(mask, b_old[rows], -1)
    tb_new = np.where(mask, b_new[rows], -1)
    tl_old = np.where(mask, l_old[rows], False)
    tl_new = np.where(mask, l_new[rows], False)
    td_old = np.where(mask, d_old[rows], 0)
    td_new = np.where(mask, d_new[rows], 0)
    old_leader = np.where(
        tl_old.any(1), tb_old[np.arange(len(touched)), tl_old.argmax(1)], -1
    )
    new_leader = np.where(
        tl_new.any(1), tb_new[np.arange(len(touched)), tl_new.argmax(1)], -1
    )
    moved = mask & (tb_old != tb_new)
    data = np.where(moved, disk_bytes[rows], 0.0).sum(1)
    disk_changed = mask & (tb_old == tb_new) & (td_old != td_new)
    t_topic = topic[rows[:, 0]]

    # leader-first ordering, vectorized: stable sort on (2=pad, 1=follower,
    # 0=leader) keeps the preferred order among followers while hoisting the
    # leader to the head — then materialize via tolist() (numpy scalar
    # indexing inside a 100k-row loop would dominate the optimizer wall)
    def reorder(tb, leader):
        key = np.where(tb < 0, 2, np.where(tb == leader[:, None], 0, 1))
        idx = np.argsort(key, axis=1, kind="stable")
        return np.take_along_axis(tb, idx, axis=1)

    n_valid = mask.sum(1)
    ob = reorder(tb_old, old_leader)
    nb = reorder(tb_new, new_leader)
    has_disk = disk_changed.any(1)
    disk_rows = {
        int(k): tuple(
            (int(tb_new[k, j]), int(td_old[k, j]), int(td_new[k, j]))
            for j in np.nonzero(disk_changed[k])[0]
        )
        for k in np.nonzero(has_disk)[0]
    }

    intra_data = np.where(disk_changed, disk_bytes[rows], 0.0).sum(1)
    # replica SET change per row (has_replica_action semantics: a
    # within-partition slot swap is not a membership change)
    set_changed = (np.sort(tb_old, axis=1) != np.sort(tb_new, axis=1)).any(1)

    return ProposalSet(
        dict(
            touched=touched, topic=t_topic, old_leader=old_leader,
            new_leader=new_leader, ob=ob, nb=nb, n_valid=n_valid,
            data=data, intra_data=intra_data, set_changed=set_changed,
            moved=moved, tb_old=tb_old,
        ),
        disk_rows,
    )
