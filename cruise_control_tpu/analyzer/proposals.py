"""Execution proposals — the optimizer's output contract.

Reference: executor/ExecutionProposal.java:25 (old/new replica lists +
data-to-move) and analyzer/AnalyzerUtils.getDiff:50-117 (distribution diff
between pre- and post-optimization cluster models).  Here the diff is an
array comparison between two ClusterStates sharing the same replica axis.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.models.state import ClusterState


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment (reference executor/ExecutionProposal.java:25).

    Replica lists are broker ids, leader first (the reference keeps the new
    leader at the head of the new replica list).
    """

    partition: int
    topic: int
    old_leader: int
    new_leader: int
    old_replicas: tuple[int, ...]
    new_replicas: tuple[int, ...]
    #: per-replica (broker, old_disk, new_disk) intra-broker moves (JBOD)
    disk_moves: tuple[tuple[int, int, int], ...] = ()
    #: bytes of replica data crossing broker boundaries
    inter_broker_data_to_move: float = 0.0

    @property
    def has_replica_action(self) -> bool:
        return set(self.old_replicas) != set(self.new_replicas)

    @property
    def has_leader_action(self) -> bool:
        return self.old_leader != self.new_leader

    def to_json(self) -> dict:
        return {
            "topicPartition": {"topic": int(self.topic), "partition": int(self.partition)},
            "oldLeader": int(self.old_leader),
            "oldReplicas": [int(b) for b in self.old_replicas],
            "newReplicas": [int(b) for b in self.new_replicas],
        }


def extract_proposals(before: ClusterState, after: ClusterState) -> list[ExecutionProposal]:
    """Diff two placements into per-partition proposals
    (reference analyzer/AnalyzerUtils.getDiff:50-117)."""
    valid = np.asarray(before.replica_valid)
    part = np.asarray(before.replica_partition)[valid]
    topic = np.asarray(before.replica_topic)[valid]
    pos = np.asarray(before.replica_pos)[valid]
    b_old = np.asarray(before.replica_broker)[valid]
    b_new = np.asarray(after.replica_broker)[valid]
    l_old = np.asarray(before.replica_is_leader)[valid]
    l_new = np.asarray(after.replica_is_leader)[valid]
    d_old = np.asarray(before.replica_disk)[valid]
    d_new = np.asarray(after.replica_disk)[valid]
    disk_bytes = np.asarray(before.replica_load_leader)[valid][:, int(Resource.DISK)]

    changed = (b_old != b_new) | (l_old != l_new) | (d_old != d_new)
    touched = np.unique(part[changed])
    if touched.size == 0:
        return []

    # group replica rows by partition
    order = np.argsort(part, kind="stable")
    proposals: list[ExecutionProposal] = []
    bounds = np.searchsorted(part[order], [touched, touched + 1])
    for k, p in enumerate(touched):
        rows = order[bounds[0][k]: bounds[1][k]]
        rows = rows[np.argsort(pos[rows], kind="stable")]  # preferred order
        ol = rows[l_old[rows]]
        nl = rows[l_new[rows]]
        old_leader = int(b_old[ol[0]]) if ol.size else -1
        new_leader = int(b_new[nl[0]]) if nl.size else -1

        def ordered(brokers, leader):
            lst = [int(x) for x in brokers]
            if leader in lst:
                lst.remove(leader)
                lst.insert(0, leader)
            return tuple(lst)

        moved = rows[b_old[rows] != b_new[rows]]
        disk_moves = tuple(
            (int(b_new[r]), int(d_old[r]), int(d_new[r]))
            for r in rows
            if b_old[r] == b_new[r] and d_old[r] != d_new[r]
        )
        proposals.append(
            ExecutionProposal(
                partition=int(p),
                topic=int(topic[rows[0]]),
                old_leader=old_leader,
                new_leader=new_leader,
                old_replicas=ordered(b_old[rows], old_leader),
                new_replicas=ordered(b_new[rows], new_leader),
                disk_moves=disk_moves,
                inter_broker_data_to_move=float(disk_bytes[moved].sum()),
            )
        )
    return proposals
