"""Batched what-if evaluation — N hypothetical clusters, one device program.

The goal chain (analyzer/objective.py) is pure jnp over the ClusterState
pytree, so N scenario states of ONE shared (bucketed) shape stack into a
leading batch axis and score under `jax.vmap` in a single jitted
program: per-scenario objective + per-goal violations for the price of
one dispatch.  That is the planner's workhorse — a rightsize sweep or a
rack-loss matrix is dozens of hypotheticals, and evaluating them
sequentially would pay dispatch + transfer per scenario for arrays that
are 99% identical.

The optional `optimize=True` pass runs the FULL anneal per scenario
through the caller's GoalOptimizer: every scenario state shares the
batch shape, so the optimizer's engine cache compiles ONCE and rebinds
for the rest (observable via the `analyzer.engine-cache-*` counters —
the acceptance contract of the planner).

Supervision: the batched device call runs under the same
DeviceSupervisor the optimizer uses; a wedged device degrades to a
sequential CPU evaluation (tagged `degraded=True`) instead of hanging
the planner endpoints.  The optimize pass needs no extra handling —
GoalOptimizer.optimize already degrades itself.
"""

from __future__ import annotations

import dataclasses
import logging
import time

import numpy as np

from cruise_control_tpu.analyzer.objective import (
    DEFAULT_CHAIN,
    GoalChain,
    balancedness_score,
)
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.state import ClusterState

log = logging.getLogger(__name__)

#: goals violated above this are "failed" — the same f32-noise epsilon
#: balancedness_score and OptimizerResult.violated_goals_after use
VIOLATION_TOL = 1e-6


@dataclasses.dataclass(frozen=True)
class ScenarioOutcome:
    """What one hypothetical looks like, before and (optionally) after a fix."""

    name: str
    objective: float
    violations: np.ndarray  # f32[G] per-goal violation at current placement
    violated_goals: list
    balancedness: float
    hard_goals_satisfied: bool
    brokers_alive: int
    degraded: bool = False
    #: present when the full anneal ran: the projected post-fix cluster
    fix: dict | None = None

    def to_json(self) -> dict:
        out = {
            "name": self.name,
            "objective": self.objective,
            "violatedGoals": list(self.violated_goals),
            "balancedness": self.balancedness,
            "hardGoalsSatisfied": self.hard_goals_satisfied,
            "brokersAlive": self.brokers_alive,
        }
        if self.fix is not None:
            out["fix"] = self.fix
        return out


class ScenarioEvaluator:
    """Batch-scores scenario states on the goal chain; optionally anneals
    each through the shared GoalOptimizer."""

    def __init__(
        self,
        chain: GoalChain = DEFAULT_CHAIN,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        *,
        optimizer=None,
        supervisor=None,
        sensors=None,
        balancedness_weights: tuple[float, float] = (1.1, 1.5),
        max_scenarios: int = 32,
    ):
        """optimizer: GoalOptimizer for the optimize=True pass (its chain
        should be this chain — the facade wires both from config);
        supervisor: DeviceSupervisor shared with the optimizer so a wedged
        device degrades the whole analyzer surface coherently."""
        self.chain = chain
        self.constraint = constraint
        self.optimizer = optimizer
        self.supervisor = supervisor
        self.sensors = sensors
        self.balancedness_weights = balancedness_weights
        self.max_scenarios = max_scenarios
        import threading
        from collections import OrderedDict

        #: jitted batched program per (shape, N, varying fieldset) — the
        #: arrays are arguments, not constants, so one entry serves every
        #: batch of that geometry.  BOUNDED LRU: under topology churn and
        #: varied batch mixes an unbounded map accretes compiled XLA
        #: executables forever (the leak class the optimizer's engine
        #: cache already guards against).  Locked: the facade shares ONE
        #: evaluator across the user-task pool, and OrderedDict reordering
        #: is not thread-safe (same discipline as the engine cache's lock).
        self._batched_fns: OrderedDict = OrderedDict()
        self._batched_fns_cap = 8
        self._fns_lock = threading.Lock()
        self._cpu_fn = None
        self._single_fn = None

    # ------------------------------------------------------------------
    # batched scoring
    # ------------------------------------------------------------------

    def evaluate_states(self, states: list[ClusterState]):
        """(objectives f64[N], violations f64[N, G], degraded) for N states
        of ONE shared shape — one stacked vmap program, one dispatch."""
        import jax

        if not states:
            return np.zeros(0), np.zeros((0, len(self.chain.goals))), False
        shapes = {s.shape for s in states}
        if len(shapes) > 1:
            raise ValueError(
                f"scenario batch spans {len(shapes)} shapes; plan_shape the "
                "batch so it shares one compiled program"
            )
        sup = self.supervisor
        if sup is None:
            obj, viol = self._evaluate_on_device(states)
            return obj, viol, False
        from cruise_control_tpu.common.device_watchdog import DeviceDegradedError

        if sup.available():
            try:
                obj, viol = sup.call(
                    lambda: self._evaluate_on_device(states), op="scenario-eval"
                )
                return obj, viol, False
            except DeviceDegradedError:
                pass
        obj, viol = self._evaluate_cpu(states)
        if self.sensors is not None:
            self.sensors.counter("planner.degraded-evaluations").inc()
        return obj, viol, True

    @device_op("scenario.batch-eval")
    def _evaluate_on_device(self, states):
        import jax
        import jax.numpy as jnp

        shape = states[0].shape
        fields = [
            f.name for f in dataclasses.fields(ClusterState) if f.name != "shape"
        ]
        # scenario states alias the shared base's arrays for every field
        # their scenario did not touch (models/whatif.py dirty tracking):
        # those ride into the program ONCE; only the genuinely different
        # fields are stacked — for a typical batch that is a couple of
        # broker-axis vectors, not N copies of the model
        shared, varying = {}, {}
        for f in fields:
            vals = [getattr(s, f) for s in states]
            if all(v is vals[0] for v in vals[1:]):
                shared[f] = vals[0]
            else:
                varying[f] = jnp.asarray(np.stack([np.asarray(v) for v in vals]))
        if not varying:
            # every scenario is the identity: score the base once, fan out
            obj, viol = self._single_eval(states[0])
            return (
                np.full(len(states), float(obj), np.float64),
                np.tile(np.asarray(viol, np.float64), (len(states), 1)),
            )
        key = (shape, len(states), frozenset(varying))
        with self._fns_lock:
            fn = self._batched_fns.get(key)
            if fn is not None:
                self._batched_fns.move_to_end(key)
        if fn is None:
            chain, constraint = self.chain, self.constraint

            def batched(shared, varying):
                def one(diff):
                    s = ClusterState(shape=shape, **shared, **diff)
                    obj, viol, _ = chain.evaluate(s, constraint=constraint)
                    return obj, viol

                # lax.map, not vmap: the goal chain is segment-sum heavy,
                # and batching scatters adds a batch dimension XLA lowers
                # poorly (CPU measurably WORSE than sequential).  lax.map
                # compiles the single-state program once and loops it on
                # device — identical per-scenario numerics (pinned by the
                # scenarios bench gate), one dispatch, one host sync.
                return jax.lax.map(one, varying)

            fn = jax.jit(batched)
            with self._fns_lock:
                self._batched_fns[key] = fn
                while len(self._batched_fns) > self._batched_fns_cap:
                    self._batched_fns.popitem(last=False)
        obj, viol = jax.device_get(fn(shared, varying))
        return np.asarray(obj, np.float64), np.asarray(viol, np.float64)

    def _single_eval(self, state):
        import jax

        if getattr(self, "_single_fn", None) is None:

            def one(s):
                obj, viol, _ = self.chain.evaluate(s, constraint=self.constraint)
                return obj, viol

            self._single_fn = jax.jit(one)
        return jax.device_get(self._single_fn(state))

    # ------------------------------------------------------------------
    # calibration scoring (decision ledger, analyzer/ledger.py)
    # ------------------------------------------------------------------

    @device_op("scenario.score-state")
    def _score_state_on_device(self, state):
        import jax

        from cruise_control_tpu.models.stats import compute_stats

        if getattr(self, "_score_fn", None) is None:

            def one(s):
                obj, viol, _ = self.chain.evaluate(s, constraint=self.constraint)
                return obj, viol, compute_stats(s)

            self._score_fn = jax.jit(one)
        return jax.device_get(self._score_fn(state))

    def score_state(self, state: ClusterState):
        """(objective, per-goal violations f64[G], ClusterStats, degraded)
        of ONE measured cluster state — the calibration loop's scorer:
        the SAME goal chain + constraint the decision's prediction rode,
        evaluated in one batched dispatch (goal chain + cluster stats as
        one program), supervised like every other evaluator dispatch with
        a sequential-CPU degraded fallback."""
        import jax

        sup = self.supervisor
        if sup is None:
            obj, viol, stats = self._score_state_on_device(state)
            return float(obj), np.asarray(viol, np.float64), stats, False
        from cruise_control_tpu.common.device_watchdog import DeviceDegradedError

        if sup.available():
            try:
                obj, viol, stats = sup.call(
                    lambda: self._score_state_on_device(state),
                    op="calibration-score",
                )
                return float(obj), np.asarray(viol, np.float64), stats, False
            except DeviceDegradedError:
                pass
        from cruise_control_tpu.models.stats import compute_stats

        # degraded twin: objective/violations via the sequential-CPU
        # evaluator path, cluster stats computed on the CPU backend
        cpu = jax.local_devices(backend="cpu")[0]
        host = jax.tree.map(np.asarray, state)
        objs, viols = self._evaluate_cpu([host])
        with jax.default_device(cpu):
            stats = jax.tree.map(np.asarray, compute_stats(host))
        if self.sensors is not None:
            self.sensors.counter("planner.degraded-evaluations").inc()
        return float(objs[0]), np.asarray(viols[0], np.float64), stats, True

    def _evaluate_cpu(self, states):
        """Degraded path: sequential single-state evaluation pinned to the
        host CPU backend — same numbers, no batching, no accelerator."""
        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        if self._cpu_fn is None:

            def one(s):
                obj, viol, _ = self.chain.evaluate(s, constraint=self.constraint)
                return obj, viol

            self._cpu_fn = jax.jit(one)
        objs, viols = [], []
        with jax.default_device(cpu):
            for s in states:
                host = jax.tree.map(np.asarray, s)
                o, v = jax.device_get(self._cpu_fn(host))
                objs.append(float(o))
                viols.append(np.asarray(v, np.float64))
        return np.asarray(objs, np.float64), np.stack(viols)

    # ------------------------------------------------------------------
    # the full planner pass
    # ------------------------------------------------------------------

    def evaluate(
        self,
        base_state: ClusterState,
        scenarios,
        catalog=None,
        *,
        optimize=False,
        bucket=None,
    ) -> list[ScenarioOutcome]:
        """Apply each scenario to `base_state`, batch-score all of them,
        and anneal for the projected post-fix view.  `optimize`: one bool
        for the whole batch, or a per-scenario sequence (the facade rides
        a baseline scenario in every /simulate batch and must not pay a
        full anneal for a fix block it never serializes)."""
        from cruise_control_tpu.planner.scenario import apply_scenario, plan_shape

        scenarios = list(scenarios)
        if len(scenarios) > self.max_scenarios:
            raise ValueError(
                f"{len(scenarios)} scenarios exceed planner.max.scenarios="
                f"{self.max_scenarios}"
            )
        if isinstance(optimize, bool):
            optimize = [optimize] * len(scenarios)
        elif len(optimize) != len(scenarios):
            raise ValueError(
                f"optimize mask has {len(optimize)} entries for "
                f"{len(scenarios)} scenarios"
            )
        t0 = time.monotonic()
        shape = plan_shape(base_state, scenarios, bucket=bucket)
        if shape != base_state.shape:
            from cruise_control_tpu.models.builder import pad_state

            # pad ONCE: every scenario state then aliases this base's
            # arrays for its untouched fields, which is what lets the
            # batched program take the shared fields unstacked
            base_state = pad_state(base_state, shape)
        states = [
            apply_scenario(base_state, sc, catalog, shape=shape)
            for sc in scenarios
        ]
        objs, viols, degraded = self.evaluate_states(states)
        hard = self.chain.hard_mask()
        names = self.chain.names()
        pw, sw = self.balancedness_weights
        outcomes = []
        for i, sc in enumerate(scenarios):
            v = viols[i]
            alive = int(
                (np.asarray(states[i].broker_valid) & np.asarray(states[i].broker_alive)).sum()
            )
            fix = None
            if optimize[i] and self.optimizer is not None:
                fix = self._fix_summary(states[i])
            outcomes.append(
                ScenarioOutcome(
                    name=sc.name,
                    objective=float(objs[i]),
                    violations=v,
                    violated_goals=[n for n, x in zip(names, v) if x > VIOLATION_TOL],
                    balancedness=balancedness_score(
                        v, self.chain, priority_weight=pw, strictness_weight=sw
                    ),
                    hard_goals_satisfied=bool((v[hard] <= VIOLATION_TOL).all()),
                    brokers_alive=alive,
                    degraded=degraded,
                    fix=fix,
                )
            )
        if self.sensors is not None:
            self.sensors.counter("planner.scenarios-evaluated").inc(len(scenarios))
            self.sensors.gauge("planner.last-batch-size").set(len(scenarios))
            self.sensors.timer("planner.batch-eval-timer").update(
                time.monotonic() - t0
            )
        return outcomes

    def _fix_summary(self, state: ClusterState) -> dict:
        """Run the full anneal on one scenario state; the projected
        post-fix cluster as a summary dict.  Engine reuse across the batch
        is the point: every scenario shares the planned shape, so the
        optimizer compiles once and rebinds N-1 times."""
        result = self.optimizer.optimize(state)
        out = result.summary()
        out["violatedGoalsBefore"] = [
            n for n, v in zip(result.goal_names, result.violations_before)
            if v > VIOLATION_TOL
        ]
        hard = self.chain.hard_mask()
        after = np.asarray(result.violations_after)
        out["hardGoalsSatisfiedAfter"] = bool(
            (after[hard[: after.size]] <= VIOLATION_TOL).all()
        )
        return out
