"""Batched simulated-annealing/greedy optimization engine.

This replaces the reference's single-threaded greedy goal loop
(reference analyzer/goals/AbstractGoal.java:66-107: while(!finished)
rebalanceForBroker -> maybeApplyBalancingAction, one move tried at a time
with O(#goals) veto checks) with a TPU-shaped search:

  every step, K candidate moves (replica relocations + leadership
  transfers) are sampled and their exact objective deltas are computed IN
  PARALLEL in O(1) each — gathers against per-broker aggregates plus
  frozen per-step globals — then a maximal non-conflicting subset of
  improving moves is accepted (per-broker/per-partition rank argmin), and
  aggregates are updated by scatter.  Hundreds of moves land per step; the
  whole step is one fused XLA program under `lax.scan`.

Objective semantics match GoalChain (analyzer/objective.py): weighted
lexicographic goal violations + a dispersion tiebreaker.  The delta path
and the full-eval path (goal classes) are kept consistent by unit test
(tests/test_optimizer.py).

Simulated annealing: a candidate is accepted if delta < -T·log(u) — at
T=0 this is pure greedy improvement; early rounds use T>0 to escape the
local optima the reference needs explicit swap moves for (reference
ResourceDistributionGoal.java:502-599; SURVEY §7 hard part (b)).

Compilation model: all cluster data rides in an `EngineStatics` pytree
passed as a runtime ARGUMENT to the jitted programs — never closed over.
Closure-captured arrays become XLA constants, which (a) forces a
recompile per model generation and (b) makes those compiles pathologically
slow at 500k-replica scale.  With statics-as-arguments one Engine per
ClusterShape serves every model generation; `rebind()` swaps in fresh
data with zero recompilation (the TPU analog of the reference's proposal
precompute amortization, GoalOptimizer.java:124-175).

Shape bucketing extends that amortization across TOPOLOGY CHURN: a live
cluster creates partitions and adds brokers continuously, so exact shapes
would make nearly every generation a compile miss anyway.  Model builds
round each ClusterShape axis up to a geometric bucket
(`models.state.ShapeBucketPolicy`, config `tpu.shape.bucket.*`) and mask
the padding (replica_valid / broker_valid); sampling draws are scaled by
the RUNTIME valid counts (`EngineStatics.n_source/n_dest/n_brokers`, not
the padded axis sizes), so an exact and a bucketed build of the same
cluster produce byte-identical move trajectories — bucketing changes the
compile key and nothing else.  `GoalOptimizer` keeps compiled engines in
a bounded LRU (`tpu.engine.cache.size`) whose eviction calls `release()`
to free the evicted generation's HBM.

Execution model (fused rounds, the default): the ENTIRE multi-round
anneal is ONE device-resident XLA program — a `lax.scan` over rounds
whose body is the per-round step scan plus the between-rounds program
(aggregate refresh, sampling-plan rebuild, cheap early-stop signal), with
the temperature schedule, the authoritative full-goal-chain early stop,
and the extra-polish-rounds loop expressed in-graph as cond-masked
rounds.  The host dispatches twice (init, fused run), then performs ONE
blocking device sync to fetch scalar per-round stats; the final carry
stays on device for the result report / proposal diff to consume, so
host-side extraction overlaps the tail of device work.  The EngineCarry
input is donated (`donate_argnums`) so HBM holds a single placement copy
at 500k-replica scale instead of one per dispatch.

The legacy Python round loop (`fused_rounds=False`) dispatches one scan
per round and syncs O(num_rounds) times.  It remains the right tool for
fused-vs-legacy parity testing, per-round host-side debugging (inspect
the carry between rounds), and experimenting with host-driven schedules;
both paths share every traced sub-program, temperatures, and RNG chain,
so at T=0 with a fixed seed they produce identical move trajectories.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import logging
import threading
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.objective import GoalChain, TIE_WEIGHT
from cruise_control_tpu.analyzer.options import DEFAULT_OPTIONS, OptimizationOptions
from cruise_control_tpu.common import collectives
from cruise_control_tpu.common.blackbox import RECORDER as _BLACKBOX
from cruise_control_tpu.common.device_watchdog import device_op
from cruise_control_tpu.common.dispatch import count_dispatch
from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
from cruise_control_tpu.config.balancing import BalancingConstraint, DEFAULT_CONSTRAINT
from cruise_control_tpu.models.aggregates import compute_aggregates
from cruise_control_tpu.models.state import (
    ClusterShape,
    ClusterState,
    validate_on_device,
)
from cruise_control_tpu.models.stats import compute_stats


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Search knobs (no reference analog — the reference search is greedy)."""

    num_candidates: int = 2048  # K sampled moves per step
    leadership_candidates: int = 512  # of which leadership transfers
    swap_candidates: int = 512  # of which replica swaps (escape local optima,
    # reference ResourceDistributionGoal.java:502-599; clamped so at least
    # one plain relocation candidate remains)
    steps_per_round: int = 64  # jitted scan length
    num_rounds: int = 10  # python-level rounds (aggregates re-derived each round)
    init_temperature_scale: float = 1e-2  # T0 = scale * initial objective
    temperature_decay: float = 0.5  # per-round geometric decay; last round T=0
    seed: int = 0
    #: movement pricing — the reference only moves what a goal demands and
    #: its executor caps concurrent moves (executor/Executor.java:485-510,
    #: ExecutionProposal data-to-move).  SA needs movement priced into the
    #: objective or it random-walks placement for free.  A move away from a
    #: replica's ORIGINAL broker/leader pays the cost; moving back refunds it.
    replica_move_cost: float = 0.5  # per relocated replica, /n_valid
    leadership_move_cost: float = 1.0  # per relocated partition leadership, /n_valid
    #: fraction of replica-move candidates importance-sampled from brokers
    #: with the largest objective contribution (rest stay uniform); the
    #: sampling plan is refreshed every round
    importance_fraction: float = 0.5
    #: intra-broker (JBOD) mode: candidates move replicas between a broker's
    #: own logdirs instead of between brokers (reference rebalance_disk
    #: semantics, AnalyzerConfig.java:236 default.intra.broker.goals);
    #: leadership/swap candidates are disabled
    intra_broker: bool = False
    #: stop annealing once the weighted goal violations (objective minus the
    #: dispersion tiebreaker) fall to this level — remaining rounds could
    #: only polish dispersion, which no goal measures.  Aligned with the
    #: 1e-6 "goal satisfied" tolerance used by balancedness_score and the
    #: bench's violated_goals_after (f32 noise floor at 500k-replica scale
    #: is ~1e-8..1e-7; see analyzer/objective.py).  <0 disables.
    early_stop_violations: float = 1e-6
    #: extra T=0 polish rounds run past num_rounds while the FULL goal chain
    #: still reports violations and each round keeps improving.  The
    #: reference optimizes every goal to completion rather than on a fixed
    #: budget (AbstractGoal.optimize loops until finished); a fixed schedule
    #: tuned for steady-state rebalances runs out on much-worse starts
    #: (mass decommissions).  0 disables.
    max_extra_rounds: int = 8
    #: run the whole multi-round anneal as ONE device-resident program
    #: (scan-of-scans with in-graph aggregate refresh, sampling-plan
    #: rebuild, temperature schedule, early stop, and extra polish rounds;
    #: the EngineCarry input is donated so HBM holds one placement copy).
    #: False selects the legacy Python round loop — one dispatch + one
    #: blocking sync per round — kept for parity testing, per-round
    #: debugging, and host-side schedule experiments.
    fused_rounds: bool = True
    #: learned move-acceptance prior (streaming controller): replica-move
    #: DESTINATION draws mix a per-(source-topic, destination) categorical
    #: fitted from past anneal trajectories / executed proposals
    #: (controller/prior.py) into the uniform draw.  Trace-static: False
    #: (the default) keeps the traced step program byte-identical to the
    #: pre-prior engine; True adds the prior gather/searchsorted ops but a
    #: COLD prior (mix 0) still reproduces the uniform draw stream
    #: bit-for-bit — the uniform branch consumes the same key with the
    #: same arithmetic, and the prior's extra draws ride keys derived via
    #: fold_in that no other stream reads (pinned by tests).
    prior_enabled: bool = False
    #: convergence diagnostics (config analyzer.diagnostics.enabled): the
    #: fused program's per-round outputs additionally carry the full-chain
    #: objective, the per-goal violation vector at each round boundary,
    #: acceptance counts by move kind, and prior-draw usage — riding the
    #: run's existing single host extraction, ZERO extra blocking syncs.
    #: Trace-static: False keeps the traced program and its outputs
    #: byte-identical to today's; True adds only read-only reductions
    #: (no RNG keys are split, no placement arithmetic changes), so
    #: placements are byte-identical to the off path — pinned by
    #: tests/test_ledger.py across plain, segmented, and mesh runs.
    diagnostics: bool = False
    #: mixed-precision goal scoring (config analyzer.precision.score.dtype):
    #: "bfloat16" accumulates the goal-score weighted sums — the
    #: `_broker_terms` inner loop (inlined ~8x into the step program) and
    #: the goal chain's objective reduction — in bf16, halving the hot
    #: loop's accumulation bandwidth.  Parity-safe subset only: threshold
    #: compares, ceil/floor banding, violation vectors, and RNG arithmetic
    #: stay f32.  Trace-static: the default "float32" takes the original
    #: code path so its traced program is byte-identical to the pre-knob
    #: engine (the fp32 fallback pin); the bf16 objective must track f32
    #: within analyzer.precision.tolerance (the tolerance gate, pinned by
    #: tests/test_optimizer.py and the streaming bench).
    score_dtype: str = "float32"

    def __post_init__(self):
        # round-count knobs validated in ONE place: both the in-graph
        # (fused) early stop and the legacy host-side early stop derive
        # their round budgets from these values via `extra_round_budget`
        # and `early_stop_tol`, so the two paths cannot disagree on how
        # many rounds may run
        if self.num_rounds < 1:
            raise ValueError(f"num_rounds must be >= 1, got {self.num_rounds}")
        if self.steps_per_round < 1:
            raise ValueError(
                f"steps_per_round must be >= 1, got {self.steps_per_round}"
            )
        if self.max_extra_rounds < 0:
            raise ValueError(
                f"max_extra_rounds must be >= 0, got {self.max_extra_rounds}"
            )
        if self.num_candidates < 1:
            raise ValueError(
                f"num_candidates must be >= 1, got {self.num_candidates}"
            )
        if self.score_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"score_dtype must be 'float32' or 'bfloat16', got "
                f"{self.score_dtype!r}"
            )

    @property
    def extra_round_budget(self) -> int:
        """Extra T=0 polish rounds actually runnable.  The extra-rounds
        loop is gated on the early-stop violation signal, so disabling
        early stop (early_stop_violations < 0) disables extra rounds with
        it — in BOTH round-loop implementations."""
        return self.max_extra_rounds if self.early_stop_violations >= 0.0 else 0

    @property
    def early_stop_tol(self) -> float:
        """The early-stop threshold as the f32 value both paths compare
        against.  The fused in-graph compare is f32; the legacy host
        compare must use the same quantized constant or the two could
        disagree on round counts at the boundary."""
        return float(np.float32(self.early_stop_violations))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "replica_broker",
        "replica_is_leader",
        "replica_disk",
        "broker_load",
        "broker_replica_count",
        "broker_leader_count",
        "broker_potential_nw_out",
        "broker_leader_bytes_in",
        "broker_topic_count",
        "part_rack_count",
        "disk_load",
        "host_load",
        "key",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class EngineCarry:
    """Mutable placement + incremental aggregates carried through lax.scan."""

    replica_broker: jax.Array
    replica_is_leader: jax.Array
    replica_disk: jax.Array
    broker_load: jax.Array  # f32[B, 4] (includes dead brokers' stranded load)
    broker_replica_count: jax.Array  # i32[B]
    broker_leader_count: jax.Array  # i32[B]
    broker_potential_nw_out: jax.Array  # f32[B]
    broker_leader_bytes_in: jax.Array  # f32[B]
    broker_topic_count: jax.Array  # i32[T, B]
    part_rack_count: jax.Array  # i32[P, num_racks]
    disk_load: jax.Array  # f32[B, D]
    host_load: jax.Array  # f32[H, 4]
    key: jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "state",
        "part_replicas",
        "alive",
        "dest_ids",
        "dest_ok",
        "lead_ok",
        "topic_movable",
        "host_multi",
        "host_cap",
        "total_cap",
        "n_alive",
        "n_valid",
        "total_disk_cap",
        "n_source",
        "n_dest",
        "n_brokers",
        "prior_dst_cdf",
        "prior_mix",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class EngineStatics:
    """Per-model-generation inputs, passed (not closed over) into jit."""

    state: ClusterState
    part_replicas: jax.Array  # i32[P, max_rf]
    alive: jax.Array  # bool[B] valid & alive
    dest_ids: jax.Array  # i32[B] allowed destination ids, cyclically padded
    dest_ok: jax.Array  # bool[B] allowed-destination mask (swap feasibility)
    lead_ok: jax.Array  # bool[B]
    topic_movable: jax.Array  # bool[T]
    host_multi: jax.Array  # bool[H]
    host_cap: jax.Array  # f32[H, 4]
    total_cap: jax.Array  # f32[4]
    n_alive: jax.Array  # f32 scalar
    n_valid: jax.Array  # f32 scalar
    total_disk_cap: jax.Array  # f32 scalar
    #: i32 scalar — leading replica slots uniform source draws cover (the
    #: valid prefix when replicas are front-packed, else the full padded R).
    #: Sampling ``floor(u * n_source)`` instead of ``randint(0, R)`` makes
    #: candidate streams independent of the PADDED R: an exact and a
    #: shape-bucketed build of the same cluster draw identical candidates,
    #: so bucketing changes nothing but the compile key (and no draws are
    #: wasted on padding rows).
    n_source: jax.Array
    #: i32 scalar — real entries at the head of dest_ids (same role as
    #: n_source for destination draws: padded-B invariance)
    n_dest: jax.Array
    #: i32 scalar — valid (real, front-packed) broker count; clips the
    #: importance sampler's CDF search so a u ~ 1.0 edge draw resolves to
    #: the last REAL broker under any padding
    n_brokers: jax.Array
    #: f32[T, B] per-SOURCE-TOPIC inclusive CDF over destination POSITIONS
    #: (indices into dest_ids' real head), the learned move-acceptance
    #: prior of the streaming controller; positions >= n_dest hold 1.0 so
    #: an edge draw clips onto the last real destination.  A [1, 1] zero
    #: placeholder when the engine's config has prior_enabled=False (the
    #: compile key includes the flag, so avals stay consistent per engine).
    prior_dst_cdf: jax.Array
    #: f32 scalar in [0, 1] — fraction of replica-move destination draws
    #: taken from the prior CDF instead of uniform; 0.0 (cold prior) makes
    #: the destination stream byte-identical to the uniform-only draw
    prior_mix: jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["broker_cdf", "order", "start", "count", "replica_cost", "lead_cost"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class SamplingPlan:
    """Per-round step context: importance sampling + movement pricing.

    Sampling: uniform source sampling over 500k replicas wastes almost the
    whole candidate budget near convergence (nearly all candidates touch
    already-balanced brokers).  Instead: sample a source broker from a
    categorical proportional to its current objective contribution, then a
    replica uniformly on that broker via a grouped index (order/start/count),
    all frozen at round start so the scan stays a fixed program.

    Pricing: per-move costs scale with the round-start objective — early
    rounds only accept moves with substantial gains; as the objective falls
    the price falls with it, so fine-grained fixes (and refunds for strayed
    replicas returning home) still go through.
    """

    broker_cdf: jax.Array  # f32[B] inclusive cumsum of broker probabilities
    order: jax.Array  # i32[R] replica ids grouped by broker (invalid last)
    start: jax.Array  # i32[B] group offsets into order
    count: jax.Array  # i32[B] replicas per broker
    replica_cost: jax.Array  # f32 scalar: objective price per strayed replica
    lead_cost: jax.Array  # f32 scalar: price per strayed partition leadership


def partition_replica_table(
    state: ClusterState, max_rf: int | None = None, *, host: dict | None = None
) -> np.ndarray:
    """i32[P, max_rf] replica indices per partition, padded with R.

    Membership never changes during optimization (only placement does), so
    this is built once on the host.  Mirrors reference model/Partition.java's
    replica list.  `max_rf` forces a uniform table width (the sharded engine
    needs identical shapes across shards).  `host` supplies already-fetched
    numpy copies (build_statics batches ALL device->host transfers into one
    device_get — per-array np.asarray paid seconds of transfer sync at
    500k-replica scale).
    """
    if host is not None:
        valid, part, pos = host["replica_valid"], host["replica_partition"], host["replica_pos"]
    else:
        valid, part, pos = jax.device_get(
            (state.replica_valid, state.replica_partition, state.replica_pos)
        )
    P, R = state.shape.P, state.shape.R
    if max_rf is None:
        max_rf = 1
        counts = np.bincount(part[valid], minlength=P)
        if counts.size:
            max_rf = max(1, int(counts.max()))
    table = np.full((P, max_rf), R, np.int32)
    idx = np.nonzero(valid)[0]
    slot = np.minimum(pos[idx], max_rf - 1)
    table[part[idx], slot] = idx
    return table


def _prior_fields(prior, T: int, B: int, dest_idx: np.ndarray):
    """(prior_cdf f32[T, B], prior_mix float) from a duck-typed prior
    (`.weights` f32[T, B] in broker-id space, `.mix` float) and the REAL
    destination-position list `dest_idx` — the prior-onto-positions
    conversion factored out of build_statics so the fused streaming cycle
    can refresh ONLY these two statics fields per window
    (Engine.rebind_prior) without build_statics' batched device fetch."""
    n_dest_int = int(dest_idx.size)
    prior_cdf = np.ones((T, B), np.float32)
    w = None if prior is None else getattr(prior, "weights", None)
    if w is not None:
        w = np.asarray(w, np.float32)
        if w.shape != (T, B):
            raise ValueError(
                f"prior weights shape {w.shape} != model (T={T}, B={B})"
            )
        w_pos = np.maximum(w[:, dest_idx], 0.0)  # [T, n_dest]
    else:
        w_pos = np.zeros((T, n_dest_int), np.float32)
    tot = w_pos.sum(1, keepdims=True)
    # unseen topics draw uniformly over the real destination list —
    # still a valid categorical, just a different stream than the
    # uniform branch (the mix gate decides which branch is taken)
    uni = np.full((T, n_dest_int), 1.0 / max(1, n_dest_int), np.float32)
    probs = np.where(tot > 0.0, w_pos / np.maximum(tot, 1e-12), uni)
    prior_cdf[:, :n_dest_int] = np.cumsum(probs, axis=1)
    prior_mix = float(getattr(prior, "mix", 0.0)) if prior is not None else 0.0
    if not 0.0 <= prior_mix <= 1.0:
        raise ValueError(f"prior mix must be in [0, 1], got {prior_mix}")
    return prior_cdf, prior_mix


def build_statics(
    state: ClusterState,
    options: OptimizationOptions,
    *,
    prior=None,
    prior_full_shape: bool = False,
    layout_out: dict | None = None,
) -> EngineStatics:
    """Host-side (numpy) preprocessing of one model generation.

    Every device array this needs comes down in ONE batched device_get —
    at 500k-replica scale, per-array np.asarray syncs cost seconds each
    and dominated engine construction.

    `prior` (duck-typed: `.weights` f32[T, B] in broker-id space keyed by
    this generation's topic ids, `.mix` float) is the learned
    move-acceptance prior; it is converted here onto destination
    POSITIONS because only this function knows the dest_ids layout.  With
    `prior_full_shape` False (prior_enabled=False engines) the statics
    carry a [1, 1] placeholder so the disabled program never pays a
    [T, B] transfer per rebind.
    """
    s = state.shape
    h_keys = (
        "broker_valid", "broker_alive", "broker_capacity", "broker_host",
        "disk_alive", "disk_capacity", "replica_valid", "replica_partition",
        "replica_pos",
    )
    h = dict(zip(h_keys, jax.device_get(tuple(getattr(state, k) for k in h_keys))))
    alive = h["broker_valid"] & h["broker_alive"]
    cap = h["broker_capacity"]
    dest = alive & options.dest_allowed(state)
    dest_idx = np.nonzero(dest)[0].astype(np.int32)
    if dest_idx.size == 0:
        dest_idx = np.nonzero(alive)[0].astype(np.int32)
    if dest_idx.size == 0:
        dest_idx = np.zeros(1, np.int32)
    # cyclic pad to [B]: uniform sampling over the padded list stays uniform
    # over the allowed set while the array shape stays generation-invariant
    dest_pad = dest_idx[np.arange(s.B) % dest_idx.size]
    host = h["broker_host"]
    valid_b = h["broker_valid"]
    bph = np.bincount(host[valid_b], minlength=s.num_hosts)
    host_cap = np.zeros((s.num_hosts, NUM_RESOURCES), np.float32)
    np.add.at(host_cap, host[valid_b & alive], cap[valid_b & alive])
    dmask = h["disk_alive"] & alive[:, None]
    # shape-invariant sampling bounds: uniform draws cover only the valid
    # replica prefix / real destination list, so the padded sizes never
    # leak into the RNG stream (exact-vs-bucketed trajectory parity)
    n_valid_int = int(h["replica_valid"].sum())
    front_packed = bool(h["replica_valid"][:n_valid_int].all())
    n_source = n_valid_int if front_packed else s.R
    n_dest_int = int(dest_idx.size)
    if layout_out is not None:
        # host-side destination layout for data-only statics refreshes
        # (Engine.rebind_prior): the fused cycle path must rebuild the
        # prior CDF without re-fetching these arrays from device
        layout_out["dest_idx"] = dest_idx
    if not prior_full_shape:
        prior_cdf = np.zeros((1, 1), np.float32)
        prior_mix = 0.0
    else:
        prior_cdf, prior_mix = _prior_fields(prior, s.num_topics, s.B, dest_idx)
    return EngineStatics(
        state=state,
        part_replicas=jnp.asarray(partition_replica_table(state, host=h)),
        alive=jnp.asarray(alive),
        dest_ids=jnp.asarray(dest_pad),
        dest_ok=jnp.asarray(dest),
        lead_ok=jnp.asarray(alive & options.leadership_allowed(state)),
        topic_movable=jnp.asarray(options.topic_movable(state)),
        host_multi=jnp.asarray(bph > 1),
        host_cap=jnp.asarray(host_cap),
        total_cap=jnp.asarray((cap * alive[:, None]).sum(0) + 1e-12, dtype=jnp.float32),
        n_alive=jnp.asarray(max(1.0, float(alive.sum())), jnp.float32),
        n_valid=jnp.asarray(
            max(1.0, float(h["replica_valid"].sum())), jnp.float32
        ),
        total_disk_cap=jnp.asarray(
            float((h["disk_capacity"] * dmask).sum() + 1e-12), jnp.float32
        ),
        n_source=jnp.asarray(max(1, n_source), jnp.int32),
        n_dest=jnp.asarray(n_dest_int, jnp.int32),
        n_brokers=jnp.asarray(max(1, int(h["broker_valid"].sum())), jnp.int32),
        prior_dst_cdf=jnp.asarray(prior_cdf),
        prior_mix=jnp.asarray(prior_mix, jnp.float32),
    )


def _weights_by_name(chain: GoalChain) -> dict[str, float]:
    return {g.name: w for g, w in zip(chain.goals, chain.weights)}


_RES_DIST_NAMES = {
    Resource.CPU: "CpuUsageDistributionGoal",
    Resource.NW_IN: "NetworkInboundUsageDistributionGoal",
    Resource.NW_OUT: "NetworkOutboundUsageDistributionGoal",
    Resource.DISK: "DiskUsageDistributionGoal",
}
_CAP_NAMES = {
    Resource.CPU: "CpuCapacityGoal",
    Resource.NW_IN: "NetworkInboundCapacityGoal",
    Resource.NW_OUT: "NetworkOutboundCapacityGoal",
    Resource.DISK: "DiskCapacityGoal",
}


@dataclasses.dataclass(frozen=True)
class _Weights:
    """Per-term weights extracted from a GoalChain (0 = goal not in chain)."""

    offline: float
    rack: float
    replica_cap: float
    cap: tuple[float, float, float, float]  # by Resource index
    pot_nw_out: float
    replica_dist: float
    leader_dist: float
    res_dist: tuple[float, float, float, float]
    topic_dist: float
    lbin_dist: float
    pref_leader: float
    intra_cap: float
    intra_dist: float
    tie: float

    @staticmethod
    def from_chain(chain: GoalChain) -> "_Weights":
        w = _weights_by_name(chain)
        return _Weights(
            offline=w.get("OfflineReplicaGoal", 0.0),
            rack=w.get("RackAwareGoal", 0.0),
            replica_cap=w.get("ReplicaCapacityGoal", 0.0),
            cap=tuple(w.get(_CAP_NAMES[Resource(i)], 0.0) for i in range(4)),
            pot_nw_out=w.get("PotentialNwOutGoal", 0.0),
            replica_dist=w.get("ReplicaDistributionGoal", 0.0),
            leader_dist=w.get("LeaderReplicaDistributionGoal", 0.0),
            res_dist=tuple(w.get(_RES_DIST_NAMES[Resource(i)], 0.0) for i in range(4)),
            topic_dist=w.get("TopicReplicaDistributionGoal", 0.0),
            lbin_dist=w.get("LeaderBytesInDistributionGoal", 0.0),
            pref_leader=w.get("PreferredLeaderElectionGoal", 0.0),
            intra_cap=w.get("IntraBrokerDiskCapacityGoal", 0.0),
            intra_dist=w.get("IntraBrokerDiskUsageDistributionGoal", 0.0),
            tie=TIE_WEIGHT * min(chain.weights),
        )


log = logging.getLogger(__name__)

#: AOT-artifact worthwhileness floor (analyzer/prewarm.py): exporting a
#: fused program costs a second trace + one background compile, which
#: only pays off where tracing is the restart bill — production-scale
#: engines.  Toy engines (unit tests, tiny demo clusters) trace in
#: well under a second and skip the artifact tier entirely; the
#: manifest/boot-prewarm tier is scale-independent and always applies.
AOT_MIN_REPLICAS = 16_384
AOT_MIN_CANDIDATES = 1_024

#: per-round scalar keys of the (non-verbose) fused program's ys output —
#: the ONE definition `_fused_rounds_body` validates its dict against and
#: `Engine._fused_out_def` rebuilds the output treedef from WITHOUT
#: tracing (the AOT-hit path must not pay the trace artifacts skip)
FUSED_YS_KEYS = ("accepted", "ran", "stopped", "temperature", "cheap")

#: the additional per-round keys the diagnostics-on fused program emits
#: (OptimizerConfig.diagnostics): full-chain objective, per-goal violation
#: vector [G], per-kind acceptance counts, and prior-draw usage — all
#: read-only reductions riding the same single host extraction
FUSED_DIAG_YS_KEYS = FUSED_YS_KEYS + (
    "objective", "goal_viol", "acc_replica", "acc_swap", "acc_lead",
    "prior_cands", "prior_acc",
)

#: budget of AUTHORITATIVE (full goal chain) early-stop checks per run when
#: the cheap O(B) gate opens but delta-folded goals still have work — shared
#: by the fused in-graph loop and the legacy host loop so the two can never
#: disagree on how many checks (and therefore rounds) may run
FULL_CHECK_BUDGET = 2

#: cap on the segmented runner's rounds-per-slice growth: bounds the
#: number of distinct slice lengths (and therefore compiled slice
#: programs) per engine to log2(cap)+1
SEGMENT_MAX_ROUNDS = 64


def snapshot_host_tree(tree):
    """Device->host fetch that OWNS its memory.  `jax.device_get` alone is
    not a snapshot: on the CPU backend it returns zero-copy numpy views of
    the device buffers, and the slice programs donate their carry — the
    next slice dispatch reuses that memory and silently rewrites the
    "checkpoint" after capture.  np.array(copy=True) pins the bytes."""
    return jax.tree.map(lambda x: np.array(x, copy=True), jax.device_get(tree))


@dataclasses.dataclass
class CarryCheckpoint:
    """Host-side snapshot of a segmented anneal at a slice boundary —
    everything a resume needs to continue the remaining round schedule
    byte-identically: the next absolute round index, the full scan state
    (carry + seg tuple) as host numpy trees, and the per-round ys rows
    already fetched.  Captured while the device is idle (the slice
    boundary IS a blocking sync), so the copy races nothing; restoring
    onto a DIFFERENT mesh width is just `device_put` under the new mesh's
    shardings — the host trees carry no placement."""

    base: int
    carry: object
    seg: tuple
    ys_parts: list
    n_chains: int = 1
    meta: dict = dataclasses.field(default_factory=dict)


class SegmentContext:
    """Preemptible-execution request for one fused anneal (the device
    scheduler's bounded-wall preemption, fleet/scheduler.py).

    `slice_budget_s` bounds each device dispatch's wall clock
    (`fleet.scheduler.slice.budget.s`): the engine splits the round
    schedule into slices sized so one slice stays within the budget.
    `checkpoint` is called between slices on the dispatching thread — the
    scheduler uses it to pause this run while an URGENT request takes the
    device, so an urgent anneal never waits on more than ONE slice of
    background work.  The callback may block; when it returns, the run
    resumes from the carried scan state, byte-identically.

    Mesh fault tolerance (`tpu.mesh.ft.*`) rides the same boundaries:
    with `snapshot_every` > 0 and a `snapshot_sink`, every Nth slice
    boundary captures a host-side CarryCheckpoint (via the engine-supplied
    `capture` thunk) and hands it to the sink on a background thread —
    bounded to ONE in-flight persist (a due snapshot is skipped, not
    queued, while the previous one is still persisting).  Capture wall
    feeds `checkpoint_clock` so the supervisor excludes it from the hang
    budget like pause clocks.  `snapshot_every=0` (the default) is
    byte-for-byte today's behavior: `offer_snapshot` returns on one
    predicate with zero extra device work."""

    __slots__ = (
        "slice_budget_s", "checkpoint", "snapshot_every", "snapshot_sink",
        "checkpoint_clock", "snapshots_taken", "snapshots_skipped",
        "snapshot_seconds", "_snapshot_boundary", "_snapshot_worker",
        "_snapshot_lock",
    )

    def __init__(
        self,
        slice_budget_s: float,
        checkpoint=None,
        *,
        snapshot_every: int = 0,
        snapshot_sink=None,
        checkpoint_clock=None,
    ):
        self.slice_budget_s = slice_budget_s
        self.checkpoint = checkpoint
        self.snapshot_every = int(snapshot_every)
        self.snapshot_sink = snapshot_sink
        self.checkpoint_clock = checkpoint_clock
        self.snapshots_taken = 0
        self.snapshots_skipped = 0
        self.snapshot_seconds = 0.0
        self._snapshot_boundary = 0
        self._snapshot_worker = None
        self._snapshot_lock = threading.Lock()

    def offer_snapshot(self, capture) -> None:
        """Engine hook at a slice boundary (device idle): maybe capture a
        CarryCheckpoint via `capture()` and persist it in the background."""
        if self.snapshot_every <= 0 or self.snapshot_sink is None:
            return
        with self._snapshot_lock:
            self._snapshot_boundary += 1
            if self._snapshot_boundary % self.snapshot_every:
                return
            worker = self._snapshot_worker
            if worker is not None and worker.is_alive():
                # one in-flight snapshot: skip, never queue — a slow sink
                # must not stack copies of a 500k-replica carry
                self.snapshots_skipped += 1
                return
            t0 = time.monotonic()
            payload = capture()
            sink = self.snapshot_sink

            def persist():
                try:
                    sink(payload)
                except Exception:  # noqa: BLE001 — checkpointing must never
                    # take down the run it protects
                    log.warning("carry snapshot sink failed", exc_info=True)

            worker = threading.Thread(
                target=persist, daemon=True, name="carry-snapshot"
            )
            self._snapshot_worker = worker
            worker.start()
            dt = time.monotonic() - t0
            self.snapshots_taken += 1
            self.snapshot_seconds += dt
            if self.checkpoint_clock is not None:
                self.checkpoint_clock.add(dt)

    def wait_snapshot(self, timeout_s: float = 10.0) -> None:
        """Block until any in-flight persist finishes (run teardown /
        tests) — never raises."""
        worker = self._snapshot_worker
        if worker is not None:
            worker.join(timeout_s)


#: ambient segmented-execution request, set by the device scheduler
#: around a granted non-urgent dispatch.  A contextvar (not a thread
#: local) because the DeviceSupervisor runs the engine body on a worker
#: thread with the caller's context COPIED in — the seam must survive
#: that hop.  None (the default) keeps every run on the plain fused
#: path, byte-for-byte.
_SEGMENT_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "engine_segment_context", default=None
)


def current_segment_context() -> SegmentContext | None:
    return _SEGMENT_CTX.get()


@contextlib.contextmanager
def segmented_execution(ctx: SegmentContext):
    """Run the enclosed dispatches in wall-bounded preemptible slices.
    The single-device fused path and the mesh layer's fused path
    (parallel/mesh.py `_run_segmented`) honor it — a mesh slice is a
    whole shard_map program, never a split collective; everything else
    ignores the context."""
    token = _SEGMENT_CTX.set(ctx)
    try:
        yield
    finally:
        _SEGMENT_CTX.reset(token)


class _FlatCallAdapter:
    """Adapter giving an AOT-deserialized FLAT executable the plain
    fused program's (statics, carry) -> (carry, ys) calling convention.

    The exported artifact is serialized over flat leaf tuples (custom
    pytree registrations do not survive jax.export serialization across
    processes); this adapter re-flattens/unflattens at the boundary.
    Always wrapped in `_WarmedFn`, so any drift between the artifact and
    the live avals falls back to the plain jit path."""

    __slots__ = ("_compiled", "_out_def")

    def __init__(self, compiled, out_def):
        self._compiled = compiled
        self._out_def = out_def

    def __call__(self, sx, carry):
        out = self._compiled(*jax.tree.leaves((sx, carry)))
        return jax.tree.unflatten(self._out_def, list(out))


class _WarmedFn:
    """A precompiled engine program with the plain jit as safety net.

    The compiled executable skips Python re-tracing; any call-time mismatch
    (aval/sharding drift the warm-up avals did not anticipate) falls back
    to the ordinary jit path, which recompiles correctly.  `on_fallback`
    (optional) fires once per fallback call — the engine uses it to keep
    the cold-start trace accounting honest when an AOT-served program
    turns out stale at call time (a trace IS paid then, on the request
    path, and boot_report must say so)."""

    __slots__ = ("_compiled", "_jit", "_on_fallback")

    def __init__(self, compiled, jit_fn, on_fallback=None):
        self._compiled = compiled
        self._jit = jit_fn
        self._on_fallback = on_fallback

    def __call__(self, *args):
        try:
            return self._compiled(*args)
        except Exception:  # noqa: BLE001 — warm path is an optimization only
            if self._on_fallback is not None:
                try:
                    self._on_fallback()
                except Exception:  # noqa: BLE001 — accounting must not block
                    pass
            return self._jit(*args)

    def __getattr__(self, item):  # .trace/.lower passthrough for tooling
        return getattr(self._jit, item)


class _WarmPool:
    """Shared priority warm pool: background compile of engine programs.

    ONE process-wide pool (not one per engine): boot prewarm enqueues
    many engines at once, and the ACTIVE bucket's programs must compile
    before any next-bucket speculation — a heap ordered by (priority,
    submission order) gives exactly that; equal priorities keep today's
    FIFO arrival order.  Lower priority value = compiles earlier.

    Starvation guard: in-flight compiles are not preempted, so a
    FOREGROUND submission (priority <= 0 — a live request's engine, the
    boot prewarm's active bucket) that finds every worker busy spawns an
    extra worker, up to `MAX_WORKERS` — a blocked `run()` must never
    wait minutes behind a speculative bucket's compile.

    DAEMON worker threads, not ThreadPoolExecutor: concurrent.futures
    joins its (non-daemon) workers at interpreter exit, so a compile
    stuck on an unresponsive device would block process shutdown forever.
    Warm-up must never outlive the process.
    """

    #: cap on demand-grown workers (the old per-engine pools ran 2 per
    #: engine; a handful of concurrent foreground engines is the realistic
    #: worst case, and compiles release the GIL in C++ anyway)
    MAX_WORKERS = 8

    def __init__(self):
        import itertools
        import threading

        self._cond = threading.Condition()
        self._heap: list = []
        self._seq = itertools.count()
        self._workers = 0
        self._busy = 0

    def submit(self, thunk, *, priority: int = 0):
        import concurrent.futures as cf
        import heapq

        import threading

        fut = cf.Future()
        spawn = False
        with self._cond:
            heapq.heappush(self._heap, (priority, next(self._seq), fut, thunk))
            if (
                priority <= 0
                and self._busy >= self._workers
                and self._workers < self.MAX_WORKERS
            ):
                # reserve the slot INSIDE the lock: two racing foreground
                # submits must provision two workers, not both observe
                # the same count and spawn one
                self._workers += 1
                spawn = True
            self._cond.notify()
        if spawn:
            threading.Thread(
                target=self._work, daemon=True, name="engine-warm-grown"
            ).start()
        return fut

    def ensure_workers(self, n: int) -> None:
        import threading

        with self._cond:
            n = min(n, self.MAX_WORKERS)
            spawn = max(0, n - self._workers)
            self._workers += spawn
        for i in range(spawn):
            threading.Thread(
                target=self._work, daemon=True, name=f"engine-warm-{i}"
            ).start()

    def _work(self):
        import heapq

        while True:
            with self._cond:
                while not self._heap:
                    self._cond.wait()
                _, _, fut, thunk = heapq.heappop(self._heap)
                self._busy += 1
            if not fut.set_running_or_notify_cancel():
                with self._cond:
                    self._busy -= 1
                continue
            try:
                fut.set_result(thunk())
            except BaseException as e:  # noqa: BLE001 — surface via _fn
                fut.set_exception(e)
            finally:
                with self._cond:
                    self._busy -= 1


_WARM_POOL = _WarmPool()


def warm_pool_submit(thunk, *, priority: int = 0, workers: int = 2):
    """Run `thunk` on the shared warm pool; returns its Future.  The
    engine variants' compile targets and the AOT export task all ride
    this one queue, so priority ordering holds across engines."""
    _WARM_POOL.ensure_workers(max(1, workers))
    return _WARM_POOL.submit(thunk, priority=priority)


def start_warm_pool(targets, *, workers: int = 2, priority: int = 0):
    """Trace+lower+compile jitted programs on the shared warm pool.

    targets: [(name, jit_fn, avals)]; returns {name: Future[compiled]}.
    The ONE warm-overlap pool every engine variant shares: the plain
    Engine warms its fused/scan programs through it and the mesh layer
    (parallel/mesh.py) warms its shard_map'd whole-anneal program through
    the same helper, so ahead-of-use tracing always overlaps the caller's
    serial prelude the same way.  `priority` orders targets ACROSS
    engines (boot prewarm: the active bucket first, next-bucket
    speculation last); within one call, list order is preserved.
    """
    return {
        name: warm_pool_submit(
            lambda fn=fn, av=av: fn.trace(*av).lower().compile(),
            priority=priority,
            workers=workers,
        )
        for name, fn, av in targets
    }


def _relu(x):
    return jnp.maximum(x, 0.0)


def _uniform_idx(key: jax.Array, shape, n: jax.Array) -> jax.Array:
    """Uniform i32 indices in [0, n) with n a TRACED bound (n >= 1).

    `randint(0, axis_size)` would bake the PADDED axis size into the draw,
    making shape-bucketed and exact builds of the same cluster diverge;
    scaling a unit uniform by the runtime count keeps the candidate stream
    identical across padded shapes (and wastes no draws on padding rows).
    """
    u = jax.random.uniform(key, shape)
    return jnp.minimum((u * n.astype(jnp.float32)).astype(jnp.int32), n - 1)


class Engine:
    """Compiled optimization engine bound to one ClusterShape.

    Trace-static: shape, goal weights, constraint thresholds, search
    config.  Runtime: EngineStatics (cluster data) + EngineCarry.  Reuse
    the same Engine across model generations via `rebind(state)`; only a
    changed ClusterShape (padded sizes) triggers recompilation.
    """

    #: mesh axis the replica/partition arrays are sharded over, or None
    #: (replicated model).  A CLASS attribute: the model-sharded twin
    #: (parallel/model_shard.py) shares this engine's __dict__ and
    #: overrides it at class level, so the plain engine's traced programs
    #: never see a collective.
    _model_axis: str | None = None

    def __init__(
        self,
        state: ClusterState,
        chain: GoalChain,
        constraint: BalancingConstraint = DEFAULT_CONSTRAINT,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        config: OptimizerConfig = OptimizerConfig(),
        prior=None,
        prewarm_store=None,
    ):
        self.chain = chain
        self.constraint = constraint
        self.config = config
        self.w = _Weights.from_chain(chain)
        self.shape: ClusterShape = state.shape
        # effective candidate split (leadership + swap carved out of K);
        # swaps never take more than half the non-leadership budget so plain
        # relocations — the workhorse moves — keep a healthy share
        if config.intra_broker:
            # disk rebalancing: only intra-broker disk moves make sense
            self.K_l, self.K_s, self.K_r = 0, 0, config.num_candidates
        else:
            self.K_l = min(config.leadership_candidates, config.num_candidates - 1)
            self.K_s = min(
                config.swap_candidates, max(0, (config.num_candidates - self.K_l) // 2)
            )
            self.K_r = config.num_candidates - self.K_l - self.K_s
        self.d_thresh = float(constraint.capacity_threshold[int(Resource.DISK)])
        #: host-side destination layout of the CURRENT statics (filled by
        #: build_statics) — rebind_prior's no-device-fetch prior refresh
        self._statics_layout: dict = {}
        self.statics = build_statics(
            state, options, prior=prior, prior_full_shape=config.prior_enabled,
            layout_out=self._statics_layout,
        )
        self._scan = jax.jit(self._scan_impl)
        self._jit_refresh = jax.jit(self._refresh_impl)
        self._jit_objective = jax.jit(self._objective_impl)
        self._jit_plan = jax.jit(self._plan_impl)
        self._jit_violations = jax.jit(self._violations_impl)
        self._jit_cheap_violations = jax.jit(self._cheap_violations_impl)
        self._jit_round_prep = jax.jit(self._round_prep_impl)
        self._jit_init = jax.jit(self._init_impl)
        self._jit_init_from = jax.jit(self._init_from_impl)
        self._jit_eval = jax.jit(self._eval_impl)
        # the fused whole-anneal program: the carry is DONATED — its
        # buffers are reused for the output placement, so HBM holds one
        # EngineCarry at 500k-replica scale, not one per dispatch
        self._jit_run_fused = jax.jit(self._run_fused_impl, donate_argnums=(1,))
        self._jit_run_fused_verbose = None  # built lazily (adds per-round eval)
        # the fused STREAMING-CYCLE program (delta scatter + warm re-anneal
        # + reports + extraction payload as ONE dispatch): the live load
        # arrays are donated — the scatter rewrites them in place, exactly
        # like LiveState's standalone scatter program
        self._jit_run_cycle = jax.jit(self._cycle_impl, donate_argnums=(1, 2))
        #: cached (statics, cycle-statics, zero-loads placeholder) triple
        #: backing _cycle_statics
        self._cycle_sx: tuple | None = None
        #: segmented (preemptible) execution programs, built lazily on the
        #: first scheduler-granted slice run: the init program plus one
        #: slice program per rounds-per-slice length (powers of two)
        self._jit_seg_init = None
        self._seg_fns: dict[int, object] = {}
        self._warm_futures: dict | None = None
        #: analyzer/prewarm.py PrewarmStore — when present, precompile
        #: loads/saves the fused program's AOT artifact (warm-pool workers
        #: only; the request path never touches an artifact)
        self._prewarm_store = prewarm_store
        #: one trace-accounting record per engine (the fused program is
        #: jit-cached after its first trace, so later runs are not traces)
        self._fused_trace_recorded = False

    # ------------------------------------------------------------------
    # ahead-of-use compilation (warm start)
    # ------------------------------------------------------------------

    def precompile_async(self, *, priority: int = 0) -> None:
        """Trace+lower+compile every engine program on background threads,
        from abstract shapes only (no cluster data touched).

        The warm-start story: a restarted service pays Python tracing +
        XLA-cache loading before its first proposal (the reference's JVM
        never restarts its compiler — GoalOptimizer.java:124-175 amortizes
        via the precompute loop).  Kicking this off as soon as the engine
        exists lets that work overlap the optimizer's own serial prelude
        (input validation, before-stats report, host fetches): tracing in
        the pool interleaves with main-thread tracing under the GIL, and
        the XLA compile / persistent-cache load phases (GIL-released C++)
        run truly in parallel.  `run()` waits per-program via `_fn`, so
        programs are consumed in the same order they are submitted.
        `priority` orders this engine's compiles against other engines on
        the shared pool (boot prewarm: active bucket first).

        AOT (analyzer/prewarm.py, config tpu.prewarm.*): with a
        PrewarmStore bound, the fused program's serialized jax.export
        artifact is tried FIRST — a warm-disk restart skips Python
        tracing, not just the XLA compile.  The round-4 in-line attempt
        at this regressed warm start and broke multi-device modes
        (VERDICT r4) because deserialization ran on the request path and
        artifacts had no staleness key; now loads run only HERE (a
        warm-pool worker), are keyed strictly on (bucket, config,
        chain/constraint, jax version, platform, exact avals), and any
        drift or corruption falls back to the fresh trace+compile below
        — with `_WarmedFn`'s plain-jit fallback as the last rung, so
        correctness never depends on an artifact.
        """
        if self._warm_futures is not None:
            return
        sx_av = self.statics_avals()
        key_av = jax.ShapeDtypeStruct((2,), jnp.uint32)
        carry_av = jax.eval_shape(self._init_impl, sx_av, key_av)
        plan_av = jax.eval_shape(self._plan_impl, sx_av, carry_av)
        temps_av = jax.ShapeDtypeStruct((self.config.steps_per_round,), jnp.float32)
        if self.config.fused_rounds:
            # the fused run() path touches exactly two programs: init and
            # the whole-anneal scan-of-scans (everything else is inlined
            # into it).  Fused first: it is by far the largest program.
            self._warm_futures = {
                "_jit_run_fused": warm_pool_submit(
                    self._fused_warm_thunk(sx_av, carry_av, priority),
                    priority=priority,
                ),
                **start_warm_pool(
                    [("_jit_init", self._jit_init, (sx_av, key_av))],
                    priority=priority,
                ),
            }
            return
        targets = [
            # scan first: it is by far the largest program and gates the
            # first round's dispatch — worker 1 spends its whole warm-up
            # on it while worker 2 clears the small programs in use order
            ("_scan", (sx_av, carry_av, temps_av, plan_av)),
            ("_jit_init", (sx_av, key_av)),
            ("_jit_plan", (sx_av, carry_av)),
            ("_jit_round_prep", (sx_av, carry_av)),
            ("_jit_eval", (sx_av, carry_av)),
        ]
        self._warm_futures = start_warm_pool(
            [(name, getattr(self, name), av) for name, av in targets],
            priority=priority,
        )

    # ------------------------------------------------------------------
    # AOT-serialized fused program (analyzer/prewarm.py)
    # ------------------------------------------------------------------

    def _bucket_key(self) -> str:
        from cruise_control_tpu.analyzer.prewarm import bucket_key

        return bucket_key(self.shape)

    def _record_fused_trace(self, source: str) -> None:
        """Per-engine, once: count how this engine's fused program came
        to exist ("fresh" Python trace vs "aot" artifact load) — the
        cold-start SLO's observable (compilation_cache.boot_report)."""
        if self._fused_trace_recorded:
            return
        self._fused_trace_recorded = True
        from cruise_control_tpu.common.compilation_cache import record_engine_trace

        record_engine_trace(self._bucket_key(), source=source)

    def _fused_flat_inputs(self, sx_av, carry_av):
        """(leaf avals, input treedef, donated argnums) of the fused
        program over FLAT leaf tuples — the only form jax.export
        artifacts can round-trip across processes (custom pytree
        registrations do not serialize).  The carry's leaves are donated,
        matching the plain program's donate_argnums=(1,).  Pure tree
        bookkeeping: NO tracing happens here — the AOT-hit path must
        never pay the trace the artifact exists to skip."""
        leaves_av, in_def = jax.tree.flatten((sx_av, carry_av))
        n_sx = len(jax.tree.leaves(sx_av))
        donate = tuple(range(n_sx, len(leaves_av)))
        return leaves_av, in_def, donate

    def _ys_keys(self) -> tuple:
        """Per-round ys keys of this engine's (non-verbose) fused program
        — FUSED_YS_KEYS, plus the diagnostics keys when the config
        compiles convergence diagnostics in."""
        return FUSED_DIAG_YS_KEYS if self.config.diagnostics else FUSED_YS_KEYS

    def _fused_out_def(self, carry_av):
        """Output treedef of the (non-verbose) fused program — (carry,
        per-round ys dict) — constructed WITHOUT tracing: dict pytrees
        flatten by sorted key, so the key set (`_ys_keys`, the same
        constant set `_fused_rounds_body` checks its ys against) pins the
        structure.  tests/test_prewarm.py asserts this equals the traced
        structure, and the artifact fingerprint's source digest retires
        artifacts whenever this file changes."""
        ys = {k: 0 for k in self._ys_keys()}
        return jax.tree.structure((carry_av, ys))

    def aot_worthwhile(self) -> bool:
        """Whether this engine's fused program is worth an AOT artifact
        (module thresholds above; tests lower them to exercise the
        ladder at toy scale)."""
        return (
            self.shape.R >= AOT_MIN_REPLICAS
            or self.config.num_candidates >= AOT_MIN_CANDIDATES
        )

    def _fused_warm_thunk(self, sx_av, carry_av, priority: int):
        """Warm-pool thunk for the fused program: AOT artifact first
        (zero Python tracing — inputs/outputs come from tree bookkeeping
        only), fresh trace+compile otherwise (exporting the fresh program
        in the background so the NEXT restart skips the trace)."""
        store = self._prewarm_store
        aot = None
        if store is not None and self.aot_worthwhile():
            try:
                max_rf = int(self.statics.part_replicas.shape[1])
                aot = store.aot_handle(self.shape, max_rf, self.config)
            except Exception:  # noqa: BLE001 — AOT is an optimization only
                aot = None

        def thunk():
            if aot is not None:
                leaves_av, in_def, donate = self._fused_flat_inputs(
                    sx_av, carry_av
                )
                compiled = aot.load(leaves_av, donate)
                if compiled is not None:
                    self._record_fused_trace("aot")
                    return _FlatCallAdapter(
                        compiled, self._fused_out_def(carry_av)
                    )
                self._record_fused_trace("fresh")
                result = (
                    self._jit_run_fused.trace(sx_av, carry_av).lower().compile()
                )

                def flat(*leaves):
                    sx, carry = jax.tree.unflatten(in_def, list(leaves))
                    return tuple(jax.tree.leaves(self._run_fused_impl(sx, carry)))

                # persist + compile the exported twin off this (waited-on)
                # path: strictly lower priority than every pending compile
                aot.save_async(flat, leaves_av, donate, priority=priority + 1_000)
                return result
            self._record_fused_trace("fresh")
            return self._jit_run_fused.trace(sx_av, carry_av).lower().compile()

        return thunk

    def statics_avals(self):
        """Abstract shapes of the bound statics (warm-up / eval_shape input)."""
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
            self.statics,
        )

    def _fn(self, name: str):
        """The program `name`, swapped to its precompiled executable once
        the background compile finishes; plain jit when warm-up is off or
        the compile failed (correctness never depends on the warm path)."""
        futs = self._warm_futures
        if futs is not None and name in futs:
            fut = futs.pop(name)
            # a fused program that falls back AT CALL TIME (stale AOT
            # executable, aval drift under rebind) pays a fresh trace on
            # the request path — record it so the cold-start report can
            # never claim "aot" for a bucket that actually re-traced
            cb = self._record_fused_fallback if name == "_jit_run_fused" else None
            try:
                setattr(
                    self,
                    name,
                    _WarmedFn(fut.result(), getattr(self, name), on_fallback=cb),
                )
            except Exception as e:  # noqa: BLE001 — fall back to lazy jit
                log.warning("engine precompile of %s failed: %r", name, e)
        return getattr(self, name)

    def _record_fused_fallback(self) -> None:
        if getattr(self, "_fused_fallback_recorded", False):
            return
        self._fused_fallback_recorded = True
        from cruise_control_tpu.common.compilation_cache import record_engine_trace

        record_engine_trace(self._bucket_key(), source="fresh")

    # convenience for call sites that held `engine.state`
    @property
    def state(self) -> ClusterState:
        return self.statics.state

    def rebind(
        self,
        state: ClusterState,
        options: OptimizationOptions = DEFAULT_OPTIONS,
        prior=None,
    ) -> "Engine":
        """Swap in a new model generation without recompiling.  `prior`
        (see build_statics) rides the statics, so a refreshed learned
        move-acceptance prior is a data rebind too, never a compile."""
        if state.shape != self.shape:
            raise ValueError(
                f"shape changed {self.shape} -> {state.shape}; build a new Engine"
            )
        self._statics_layout = {}
        self.statics = build_statics(
            state, options, prior=prior,
            prior_full_shape=self.config.prior_enabled,
            layout_out=self._statics_layout,
        )
        return self

    def rebind_prior(self, prior) -> None:
        """Refresh ONLY the learned-prior statics fields (prior_dst_cdf /
        prior_mix) from host-side data — the steady-state fused cycle's
        per-window rebind.  A full rebind() pays build_statics' batched
        device fetch every cycle; between reflattens the placement,
        capacity, and option masks those arrays derive from cannot have
        changed, so the prior (the one statics input that evolves every
        window) is the only field worth touching.  No device_get, no
        recompile (same shapes/dtypes)."""
        if not self.config.prior_enabled or prior is None:
            return
        cdf, mix = _prior_fields(
            prior, self.shape.num_topics, self.shape.B,
            self._statics_layout["dest_idx"],
        )
        self.statics = dataclasses.replace(
            self.statics,
            prior_dst_cdf=jnp.asarray(cdf),
            prior_mix=jnp.asarray(mix, jnp.float32),
        )

    def release(self) -> None:
        """Free this engine's device buffers (engine-cache LRU eviction).

        Deletes the ENGINE-DERIVED statics arrays explicitly — dropping the
        Python reference alone leaves the HBM release to GC timing, and a
        service cycling through cluster shapes would hold every evicted
        model generation until collection.  `statics.state` is the CALLER'S
        ClusterState (also alive as result.state_before, the facade's
        proposal cache, sibling engines under other configs): its arrays
        are never deleted here, only de-referenced so GC can reclaim them
        once the caller lets go.  The engine is unusable afterwards."""
        sx = self.statics
        if sx is not None:
            for f in dataclasses.fields(EngineStatics):
                if f.name == "state":
                    continue  # caller-owned model arrays: drop the ref only
                for leaf in jax.tree.leaves(getattr(sx, f.name)):
                    try:
                        leaf.delete()
                    except Exception:  # noqa: BLE001 — already-deleted/np
                        pass
        self.statics = None
        self._warm_futures = None
        self._seg_fns = {}
        self._jit_seg_init = None

    # ------------------------------------------------------------------
    # state <-> carry
    # ------------------------------------------------------------------

    def init_carry(self, key: jax.Array) -> EngineCarry:
        return self._fn("_jit_init")(self.statics, key)

    def init_carry_from(self, key: jax.Array, placement) -> EngineCarry:
        """Carry seeded from a PRIOR placement — the streaming controller's
        warm start: the previous accepted proposal's (replica_broker,
        replica_is_leader, replica_disk) arrays become the anneal's initial
        state while the statics keep the CURRENT cluster placement, so
        movement pricing still charges strays against what the executor
        would actually have to move."""
        rb, il, dk = placement
        # REAL copies, not views: the init program forwards these arrays
        # into the carry, and the fused run DONATES the carry — without a
        # copy the donated buffers would still be aliased by the caller's
        # placement (typically a published result's state_after), which
        # the run would then scribble over
        return self._fn("_jit_init_from")(
            self.statics, key,
            jnp.array(rb, jnp.int32, copy=True),
            jnp.array(il, bool, copy=True),
            jnp.array(dk, jnp.int32, copy=True),
        )

    def _init_impl(self, sx: EngineStatics, key: jax.Array) -> EngineCarry:
        """Zero carry + aggregate refresh as ONE program (seeded from the
        statics' current placement).  Building the zero arrays eagerly
        cost ~10 tiny jit dispatches whose sub-second compiles are not
        persisted — several seconds of per-process warmup for literal
        zero-fills."""
        st = sx.state
        return self._init_from_impl(
            sx, key, st.replica_broker, st.replica_is_leader, st.replica_disk
        )

    def _init_from_impl(
        self, sx: EngineStatics, key: jax.Array, rb: jax.Array,
        il: jax.Array, dk: jax.Array,
    ) -> EngineCarry:
        """Carry seeded from an arbitrary placement (the statics' own for
        cold starts, a prior accepted placement for warm starts);
        aggregates are refreshed from IT, so the carry is exactly what a
        run that produced this placement would have left.  One program,
        one refresh (the zero aggregates are overwritten by the refresh,
        so none are computed twice)."""
        B = self.shape.B
        zeros = EngineCarry(
            replica_broker=rb,
            replica_is_leader=il,
            replica_disk=dk,
            broker_load=jnp.zeros((B, NUM_RESOURCES), jnp.float32),
            broker_replica_count=jnp.zeros(B, jnp.int32),
            broker_leader_count=jnp.zeros(B, jnp.int32),
            broker_potential_nw_out=jnp.zeros(B, jnp.float32),
            broker_leader_bytes_in=jnp.zeros(B, jnp.float32),
            broker_topic_count=jnp.zeros((self.shape.num_topics, B), jnp.int32),
            part_rack_count=jnp.zeros(self._prc_shape(), jnp.int32),
            disk_load=jnp.zeros((B, self.shape.max_disks_per_broker), jnp.float32),
            host_load=jnp.zeros((self.shape.num_hosts, NUM_RESOURCES), jnp.float32),
            key=key,
        )
        return self._refresh_impl(sx, zeros)

    def carry_to_state(self, carry: EngineCarry, sx: EngineStatics | None = None) -> ClusterState:
        st = (sx or self.statics).state
        offline = ~(
            st.broker_alive[carry.replica_broker]
            & st.disk_alive[carry.replica_broker, carry.replica_disk]
        )
        return dataclasses.replace(
            st,
            replica_broker=carry.replica_broker,
            replica_is_leader=carry.replica_is_leader,
            replica_disk=carry.replica_disk,
            replica_offline=offline & st.replica_valid,
        )

    def _prc_shape(self) -> tuple[int, int]:
        """Rows x racks of the carry's part_rack_count — the model-sharded
        twin overrides the row count with its shard-local partition rows."""
        return (self.shape.P, self.shape.num_racks)

    def _psum_if_sharded(self, x):
        """Finish a replica/partition-axis reduction: psum over the model
        axis when the model is sharded, the identity otherwise."""
        if self._model_axis is None:
            return x
        return jax.lax.psum(x, self._model_axis)

    def _refresh_impl(self, sx: EngineStatics, carry: EngineCarry) -> EngineCarry:
        state = self.carry_to_state(carry, sx)
        with collectives.model_axis_scope(self._model_axis):
            agg = compute_aggregates(state)
        hseg = jnp.where(state.broker_valid, state.broker_host, self.shape.num_hosts)
        host_load = jax.ops.segment_sum(
            agg.broker_load, hseg, num_segments=self.shape.num_hosts + 1
        )[: self.shape.num_hosts]
        return dataclasses.replace(
            carry,
            broker_load=agg.broker_load,
            broker_replica_count=agg.broker_replica_count,
            broker_leader_count=agg.broker_leader_count,
            broker_potential_nw_out=agg.broker_potential_nw_out,
            broker_leader_bytes_in=agg.broker_leader_bytes_in,
            broker_topic_count=agg.broker_topic_count,
            part_rack_count=agg.part_rack_count,
            disk_load=agg.disk_load,
            host_load=host_load,
        )

    def _objective_impl(self, sx: EngineStatics, carry: EngineCarry):
        with collectives.model_axis_scope(self._model_axis):
            obj, _, _ = self.chain.evaluate(
                self.carry_to_state(carry, sx), constraint=self.constraint,
                score_dtype=self.config.score_dtype,
            )
        return obj

    def carry_objective(self, sx: EngineStatics, carry: EngineCarry):
        """Scalar SA objective from carry aggregates (traceable, collective-free).

        Matches the delta-decomposed objective the step optimizes (broker
        terms + rack + offline + tie), NOT the full goal-chain evaluation.
        """
        g = self._globals(sx, carry)
        b = jnp.arange(self.shape.B)
        terms = self._broker_terms(
            sx,
            b,
            carry.broker_load,
            carry.broker_replica_count,
            carry.broker_leader_count,
            carry.broker_potential_nw_out,
            carry.broker_leader_bytes_in,
            g,
        ).sum()
        rack = self._psum_if_sharded(
            jnp.maximum(carry.part_rack_count - 1, 0).sum()
        ).astype(jnp.float32)
        terms += self.w.rack * rack / sx.n_valid
        st = sx.state
        offline = self._psum_if_sharded(
            (
                st.replica_valid
                & ~(
                    st.broker_alive[carry.replica_broker]
                    & st.disk_alive[carry.replica_broker, carry.replica_disk]
                )
            ).sum()
        )
        terms += self.w.offline * offline.astype(jnp.float32) / sx.n_valid
        terms += self._tie_term(sx, g["pct_sum"], g["pct_sumsq"])
        return terms

    def _cheap_violations_impl(self, sx: EngineStatics, carry: EngineCarry):
        """O(B) lower-bound signal: delta-decomposed objective minus the
        dispersion tiebreaker.  Misses goals folded into candidate deltas
        only (topic distribution), so it can read zero with work left —
        used as a gate for the authoritative check below."""
        g = self._globals(sx, carry)
        return self.carry_objective(sx, carry) - self._tie_term(
            sx, g["pct_sum"], g["pct_sumsq"]
        )

    def _violations_impl(self, sx: EngineStatics, carry: EngineCarry):
        """Authoritative early-stop signal: the WORST per-goal violation
        from the full goal chain — evaluated against the carry's incremental
        aggregates, so no O(R) segment-sums are recomputed."""
        return self._eval_impl(sx, carry)[1]

    def _eval_impl(self, sx: EngineStatics, carry: EngineCarry):
        """(full objective, worst per-goal violation) as ONE program.

        run() needs the objective at round start (temperature scaling) and
        the violation max at the early-stop gate; tracing the full goal
        chain once instead of twice halves the chain's share of the
        warm-start trace bill."""
        obj, viol = self._eval_vec_impl(sx, carry)
        return obj, jnp.max(viol)

    def _eval_vec_impl(self, sx: EngineStatics, carry: EngineCarry):
        """(full objective, per-goal violation VECTOR f32[G]) from the
        carry's incremental aggregates — the convergence-diagnostics
        variant of _eval_impl (the ledger's per-round goal trajectory)."""
        from cruise_control_tpu.models.aggregates import BrokerAggregates

        agg = BrokerAggregates(
            broker_load=carry.broker_load,
            broker_replica_count=carry.broker_replica_count,
            broker_leader_count=carry.broker_leader_count,
            broker_potential_nw_out=carry.broker_potential_nw_out,
            broker_leader_bytes_in=carry.broker_leader_bytes_in,
            broker_topic_count=carry.broker_topic_count,
            part_rack_count=carry.part_rack_count,
            disk_load=carry.disk_load,
        )
        with collectives.model_axis_scope(self._model_axis):
            obj, viol, _ = self.chain.evaluate(
                self.carry_to_state(carry, sx), agg=agg, constraint=self.constraint,
                score_dtype=self.config.score_dtype,
            )
        return obj, viol

    def _plan_impl(self, sx: EngineStatics, carry: EngineCarry):
        """Importance-sampling + movement-pricing plan from current aggregates."""
        probs, unit = self._plan_probs(sx, carry)
        return self._plan_build(sx, carry, probs, unit)

    def _plan_probs(self, sx: EngineStatics, carry: EngineCarry):
        """Per-broker sampling probabilities + movement-pricing unit — the
        O(B + T·B) half of the plan, replicated-broker math shared verbatim
        by the plain engine and the model-sharded twin."""
        st = sx.state
        B = self.shape.B
        g = self._globals(sx, carry)
        b = jnp.arange(B)
        w = self._broker_terms(
            sx,
            b,
            carry.broker_load,
            carry.broker_replica_count,
            carry.broker_leader_count,
            carry.broker_potential_nw_out,
            carry.broker_leader_bytes_in,
            g,
        )
        # stranded replicas on dead brokers/disks carry the offline-goal mass
        dead = st.broker_valid & ~sx.alive
        w = w + self.w.offline * jnp.where(
            dead, carry.broker_replica_count.astype(jnp.float32), 0.0
        ) / sx.n_valid
        # topic-distribution violations live in [T, B] cells that
        # _broker_terms cannot see — without this term the sampler goes
        # blind exactly when topic imbalance is the last goal standing
        # (post-decommission tails) and convergence stalls on uniform luck
        if self.w.topic_dist != 0.0:
            tt = self.constraint.topic_replica_count_balance_threshold
            upper = jnp.ceil(g["topic_avg"] * tt)[:, None]
            lower = jnp.floor(g["topic_avg"] * max(0.0, 2.0 - tt))[:, None]
            cnt = carry.broker_topic_count.astype(jnp.float32)
            cells = _relu(cnt - upper) + _relu(lower - cnt)  # [T, B]
            w = w + self.w.topic_dist * jnp.where(
                sx.alive, cells.sum(0), 0.0
            ) / g["total_count"]
        w = jnp.maximum(jnp.where(st.broker_valid, w, 0.0), 0.0)
        total = w.sum()
        uni = jnp.where(st.broker_valid, 1.0, 0.0)
        uni = uni / jnp.maximum(uni.sum(), 1.0)
        probs = jnp.where(total > 1e-12, w / jnp.maximum(total, 1e-12), uni)
        obj = self.carry_objective(sx, carry)
        unit = obj / sx.n_valid
        return probs, unit

    def _plan_build(self, sx: EngineStatics, carry: EngineCarry, probs, unit):
        """The O(R) half of the plan: per-broker replica counts and the
        broker-grouped replica order.  The model-sharded twin overrides
        this with shard-local counts/order + the psum'd global counts."""
        st = sx.state
        B, R = self.shape.B, self.shape.R
        seg = jnp.where(st.replica_valid, carry.replica_broker, B)
        count = jax.ops.segment_sum(
            jnp.ones(R, jnp.int32), seg, num_segments=B + 1
        )[:B]
        start = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(count)[:-1].astype(jnp.int32)]
        )
        return SamplingPlan(
            broker_cdf=jnp.cumsum(probs),
            order=jnp.argsort(seg).astype(jnp.int32),
            start=start,
            count=count,
            replica_cost=self.config.replica_move_cost * unit,
            lead_cost=self.config.leadership_move_cost * unit,
        )

    # ------------------------------------------------------------------
    # objective terms
    # ------------------------------------------------------------------

    def _globals(self, sx: EngineStatics, carry: EngineCarry):
        """Per-step frozen global scalars, O(B + T·B) from aggregates."""
        st = sx.state
        am = sx.alive
        load = jnp.where(am[:, None], carry.broker_load, 0.0)
        total_load = load.sum(0)  # [4]
        avg_pct = total_load / sx.total_cap
        counts = jnp.where(am, carry.broker_replica_count, 0)
        total_count = counts.sum()
        lcounts = jnp.where(am, carry.broker_leader_count, 0)
        total_lcount = lcounts.sum()
        lbin = jnp.where(am, carry.broker_leader_bytes_in, 0.0)
        total_lbin = lbin.sum()
        topic_total = jnp.where(am[None, :], carry.broker_topic_count, 0).sum(1)  # [T]
        dmask = st.disk_alive & am[:, None]
        total_disk_load = jnp.where(dmask, carry.disk_load, 0.0).sum()
        # dispersion tiebreaker sufficient statistics (utilization pct)
        pct = jnp.where(am[:, None], carry.broker_load / (st.broker_capacity + 1e-12), 0.0)
        return dict(
            total_load=total_load,
            avg_pct=avg_pct,
            avg_count=total_count.astype(jnp.float32) / sx.n_alive,
            total_count=jnp.maximum(total_count.astype(jnp.float32), 1.0),
            avg_lcount=total_lcount.astype(jnp.float32) / sx.n_alive,
            total_lcount=jnp.maximum(total_lcount.astype(jnp.float32), 1.0),
            avg_lbin=total_lbin / sx.n_alive,
            total_lbin=total_lbin + 1e-12,
            topic_avg=topic_total.astype(jnp.float32) / sx.n_alive,
            total_disk_load=total_disk_load + 1e-12,
            pct_sum=pct.sum(0),  # [4]
            pct_sumsq=(pct * pct).sum(0),  # [4]
        )

    def _broker_terms(self, sx, b, load, rcount, lcount, pot, lbin, g):
        """Weighted objective contribution of broker(s) b given hypothetical
        per-broker stats.  All inputs may carry a leading candidate axis.

        Mirrors (in delta-decomposable form): CapacityGoal (broker
        granularity), ReplicaCapacityGoal, PotentialNwOutGoal,
        ResourceDistributionGoal, Replica/LeaderReplicaDistributionGoal,
        LeaderBytesInDistributionGoal — see the goal classes for the
        reference citations.
        """
        st = sx.state
        w = self.w
        c = self.constraint
        cap = st.broker_capacity[b]  # [..., 4]
        alive = sx.alive[b]
        # mixed-precision accumulation (config analyzer.precision.score.dtype):
        # each goal term is still computed in f32 (the reluses against
        # capacities need the dynamic range), but the running per-broker SUM
        # of terms — the hottest accumulate in the step program, inlined ~8x —
        # may ride bf16.  f32 is the default, and `_acc` is the identity
        # there (same-dtype astype returns the input tracer), so the default
        # traced graph is byte-identical to the pre-flag one: the fp32 pin.
        lowp = self.config.score_dtype != "float32"
        acc_dt = jnp.dtype(self.config.score_dtype)
        _acc = (lambda x: x.astype(acc_dt)) if lowp else (lambda x: x)
        out = jnp.zeros(jnp.shape(b), acc_dt if lowp else jnp.float32)
        # per-resource constants as [4] vectors: one vectorized expression
        # instead of a 4-iteration Python loop — this function is inlined
        # ~8x into the step program, so per-resource unrolling multiplies
        # the traced-graph size (and with it warm-start trace time)
        cth = np.asarray(c.capacity_threshold, np.float32)
        host_res = np.asarray(
            [Resource(r).is_host_resource for r in range(NUM_RESOURCES)]
        )
        w_cap = np.asarray(w.cap, np.float32)

        # capacity goals (broker granularity; host granularity handled in
        # _host_terms for multi-broker hosts)
        single = ~sx.host_multi[st.broker_host[b]]
        excess = _relu(load - cth * cap)  # [..., 4]
        gate = alive[..., None] & (single[..., None] | ~host_res)
        out += _acc((jnp.where(gate, excess, 0.0) * (w_cap / sx.total_cap)).sum(-1))

        # replica capacity
        exc = _relu((rcount - c.max_replicas_per_broker).astype(jnp.float32))
        out += _acc(w.replica_cap * jnp.where(alive, exc, 0.0) / sx.n_valid)

        # potential nw out
        r = int(Resource.NW_OUT)
        exc = _relu(pot - c.capacity_threshold[r] * cap[..., r])
        out += _acc(w.pot_nw_out * jnp.where(alive, exc, 0.0) / sx.total_cap[r])

        # resource distribution bands
        t_bal = np.asarray(c.balance_threshold, np.float32)
        t_low = np.maximum(0.0, 2.0 - t_bal)
        w_dist = np.asarray(w.res_dist, np.float32)
        upper = g["avg_pct"] * t_bal * cap
        lower = g["avg_pct"] * t_low * cap
        term = _relu(load - upper) + _relu(lower - load)
        out += _acc(
            (
                jnp.where(alive[..., None], term, 0.0)
                * (w_dist / (g["total_load"] + 1e-12))
            ).sum(-1)
        )

        # replica count distribution
        t = c.replica_count_balance_threshold
        upper = jnp.ceil(g["avg_count"] * t)
        lower = jnp.floor(g["avg_count"] * max(0.0, 2.0 - t))
        rc = rcount.astype(jnp.float32)
        term = _relu(rc - upper) + _relu(lower - rc)
        out += _acc(w.replica_dist * jnp.where(alive, term, 0.0) / g["total_count"])

        # leader count distribution
        t = c.leader_replica_count_balance_threshold
        upper = jnp.ceil(g["avg_lcount"] * t)
        lower = jnp.floor(g["avg_lcount"] * max(0.0, 2.0 - t))
        lc = lcount.astype(jnp.float32)
        term = _relu(lc - upper) + _relu(lower - lc)
        out += _acc(w.leader_dist * jnp.where(alive, term, 0.0) / g["total_lcount"])

        # leader bytes-in distribution (upper band only)
        t = c.balance_threshold[int(Resource.NW_IN)]
        term = _relu(lbin - g["avg_lbin"] * t)
        out += _acc(w.lbin_dist * jnp.where(alive, term, 0.0) / g["total_lbin"])

        # downstream consumers (plan weights, scalar objective reduction)
        # expect f32; a no-op when the accumulator already is
        return out.astype(jnp.float32)

    def _host_terms(self, sx, h, hload):
        """Host-granularity capacity terms for multi-broker hosts
        (reference CapacityGoal host/broker split)."""
        c = self.constraint
        hcap = sx.host_cap[h]
        multi = sx.host_multi[h]
        # vectorized over resources (see _broker_terms): host resources only
        w_cap = np.asarray(
            [
                self.w.cap[r] if Resource(r).is_host_resource else 0.0
                for r in range(NUM_RESOURCES)
            ],
            np.float32,
        )
        cth = np.asarray(c.capacity_threshold, np.float32)
        excess = _relu(hload - cth * hcap)  # [..., 4]
        return (
            jnp.where(multi[..., None], excess, 0.0) * (w_cap / sx.total_cap)
        ).sum(-1)

    def _disk_terms(self, sx, b, disk_row, broker_disk_load, g):
        """Intra-broker disk goal terms for broker(s) b.

        disk_row: hypothetical f32[..., D] per-logdir load of broker b.
        broker_disk_load: its sum (for the per-broker distribution band).
        """
        st = sx.state
        w = self.w
        if w.intra_cap == 0.0 and w.intra_dist == 0.0:
            return jnp.zeros(jnp.shape(b), jnp.float32)
        dcap = st.disk_capacity[b]  # [..., D]
        dalive = st.disk_alive[b] & sx.alive[b][..., None]
        out = jnp.zeros(jnp.shape(b), jnp.float32)
        # IntraBrokerDiskCapacityGoal
        cap_term = jnp.where(
            dalive, _relu(disk_row - self.d_thresh * dcap), disk_row
        ).sum(-1)
        out += w.intra_cap * cap_term / sx.total_disk_cap
        # IntraBrokerDiskUsageDistributionGoal
        bcap = jnp.where(dalive, dcap, 0.0).sum(-1, keepdims=True)
        avg_pct = broker_disk_load[..., None] / (bcap + 1e-12)
        t = self.constraint.balance_threshold[int(Resource.DISK)]
        upper = avg_pct * t * dcap
        lower = avg_pct * max(0.0, 2.0 - t) * dcap
        dist = jnp.where(dalive, _relu(disk_row - upper) + _relu(lower - disk_row), 0.0).sum(-1)
        out += w.intra_dist * dist / g["total_disk_load"]
        return out

    def _tie_term(self, sx, pct_sum, pct_sumsq):
        """Dispersion tiebreaker: sum over resources of std of utilization pct.

        Inputs may carry a leading candidate axis — reduce ONLY the trailing
        resource axis, or every candidate's delta absorbs the whole batch's
        variance as a constant offset that vetoes small improvements.
        """
        n = sx.n_alive
        var = _relu(pct_sumsq / n - (pct_sum / n) ** 2)
        return self.w.tie * jnp.sqrt(var + 1e-18).sum(-1)

    # ------------------------------------------------------------------
    # candidate generation + delta evaluation
    # ------------------------------------------------------------------

    def _sample_sources(self, sx, key: jax.Array, n: int, plan) -> jax.Array:
        """n source replica ids; `importance_fraction` of them drawn by a
        two-stage plan draw (broker ~ categorical(objective contribution),
        then a replica uniformly on that broker), the rest uniform over the
        valid prefix (sx.n_source — see EngineStatics: padded-R invariance)."""
        k1, k3, k4, k5 = jax.random.split(key, 4)
        n_imp = (
            int(round(n * self.config.importance_fraction)) if plan is not None else 0
        )
        r = _uniform_idx(k1, (n - n_imp,), sx.n_source)
        if n_imp:
            u = jax.random.uniform(k3, (n_imp,))
            bsel = jnp.clip(
                jnp.searchsorted(plan.broker_cdf, u, side="right"), 0, sx.n_brokers - 1
            ).astype(jnp.int32)
            j = (jax.random.uniform(k4, (n_imp,)) * plan.count[bsel]).astype(jnp.int32)
            r_imp = plan.order[jnp.clip(plan.start[bsel] + j, 0, self.shape.R - 1)]
            fallback = _uniform_idx(k5, (n_imp,), sx.n_source)
            r_imp = jnp.where(plan.count[bsel] > 0, r_imp, fallback)
            r = jnp.concatenate([r, r_imp])
        return r

    def _sample_dests(
        self, sx, key: jax.Array, n: int, r: jax.Array, *, with_mask: bool = False
    ):
        """n destination POSITIONS (indices into dest_ids) for the replica
        moves whose sampled sources are `r`.

        Default (prior_enabled=False): the uniform draw over the real
        destination head — today's program, untouched.  With the learned
        move-acceptance prior compiled in, each draw takes the
        per-source-topic prior CDF with probability `prior_mix` and the
        uniform branch otherwise.  The uniform branch consumes the SAME
        key with the SAME arithmetic as the default, and the prior's two
        extra draws ride a fold_in-derived key no other stream reads, so
        a cold prior (mix 0) reproduces the uniform stream bit-for-bit —
        the controller's parity guarantee (tests/test_controller.py).

        `with_mask` (convergence diagnostics) additionally returns the
        per-draw took-the-prior-branch mask — a pure read of the existing
        mix draw, so the destination stream itself is untouched.
        """
        uni = _uniform_idx(key, (n,), sx.n_dest)
        if not self.config.prior_enabled:
            if with_mask:
                return uni, jnp.zeros((n,), bool)
            return uni
        k_m, k_p = jax.random.split(jax.random.fold_in(key, 1))
        t = self._take_rows(
            sx, None, jnp.minimum(r, self.shape.R - 1), ("topic",)
        )["topic"]
        cdf = sx.prior_dst_cdf[t]  # [n, B] per-topic inclusive CDF
        u = jax.random.uniform(k_p, (n,))
        p_idx = jnp.minimum(
            jnp.sum(u[:, None] >= cdf, axis=-1).astype(jnp.int32), sx.n_dest - 1
        )
        use = jax.random.uniform(k_m, (n,)) < sx.prior_mix
        out = jnp.where(use, p_idx, uni)
        if with_mask:
            return out, use
        return out

    def _slice_draws(self, slice_, *arrays):
        """Candidate-axis sharding (parallel/mesh.py): keep only one mesh
        shard's contiguous slice of the full-K draw vectors.

        Drawing the FULL candidate index stream from a replicated key and
        slicing afterwards keeps the stream identical for every mesh size
        — the 1-vs-N-device byte-parity guarantee — while the expensive
        per-candidate evaluation below the draws runs on K/n rows only.
        Arrays are edge-padded to n*ceil(K/n) so the tiled all_gather on
        the far side reassembles the exact full-K order (padding rows are
        discarded after the gather).  slice_=None is the single-device
        identity (the plain engine's path)."""
        if slice_ is None:
            return arrays if len(arrays) > 1 else arrays[0]
        idx, n = slice_
        out = []
        for a in arrays:
            size = -(-a.shape[0] // n)
            pad = n * size - a.shape[0]
            if pad:
                a = jnp.concatenate(
                    [a, jnp.broadcast_to(a[:1], (pad,) + a.shape[1:])]
                )
            out.append(jax.lax.dynamic_slice_in_dim(a, idx * size, size))
        return tuple(out) if len(out) > 1 else out[0]

    # ------------------------------------------------------------------
    # replica-axis row providers (the model-sharding seam)
    #
    # Candidate generation reads per-replica columns at sampled ids and
    # per-partition cells at member/partition ids.  The plain engine (and
    # the replicated mesh) fancy-index the full arrays directly; the
    # model-sharded twin (parallel/model_shard.py) overrides these four
    # methods with ownership-masked local gathers + a psum over MODEL_AXIS
    # (ids are GLOBAL; exactly one shard owns each row, the rest
    # contribute zeros).  Everything above these seams is kind-agnostic
    # replicated math, so the candidate functions themselves are shared
    # verbatim by both modes.
    # ------------------------------------------------------------------

    #: seam field -> (carry | state, attribute).  "orig_*" read the
    #: STATICS placement (movement pricing charges strays against the
    #: pre-optimization cluster, not the evolving carry).
    _ROW_SOURCES = {
        "broker": ("carry", "replica_broker"),
        "is_lead": ("carry", "replica_is_leader"),
        "disk": ("carry", "replica_disk"),
        "part": ("state", "replica_partition"),
        "topic": ("state", "replica_topic"),
        "pos": ("state", "replica_pos"),
        "valid": ("state", "replica_valid"),
        "load_leader": ("state", "replica_load_leader"),
        "load_follower": ("state", "replica_load_follower"),
        "orig_broker": ("state", "replica_broker"),
        "orig_disk": ("state", "replica_disk"),
        "orig_lead": ("state", "replica_is_leader"),
    }

    def _row_source(self, sx, carry, field):
        kind, attr = self._ROW_SOURCES[field]
        return getattr(carry if kind == "carry" else sx.state, attr)

    def _take_rows(self, sx, carry, ids, fields):
        """{field: column[ids]} for (global) replica ids `ids`."""
        return {f: self._row_source(sx, carry, f)[ids] for f in fields}

    def _take_members(self, sx, part):
        """[K, max_rf] partition->replica member table rows at (global)
        partition ids (member entries are global replica ids; >= R pads)."""
        return sx.part_replicas[part]

    def _member_field(self, sx, carry, members, field, fill):
        """Per-member column gather with the table's >= R padding masked
        to `fill` (members carry global replica ids)."""
        src = self._row_source(sx, carry, field)
        vals = src[jnp.minimum(members, self.shape.R - 1)]
        return jnp.where(members < self.shape.R, vals, fill)

    def _rack_cell(self, carry, part, rack):
        """part_rack_count[(global) partition, rack] as f32."""
        return carry.part_rack_count[part, rack].astype(jnp.float32)

    def _replica_candidates(
        self, sx, carry: EngineCarry, key: jax.Array, g, plan=None, slice_=None
    ):
        """K_r replica-move candidates -> (delta, src, dst, part, payload)."""
        st = sx.state
        K = self.K_r
        k1, k2 = jax.random.split(key)
        r = self._sample_sources(sx, k1, K, plan)
        if self.config.diagnostics:
            # same draws, plus the took-the-prior-branch mask so per-round
            # prior usage can be counted — placements untouched
            pos, from_prior = self._sample_dests(sx, k2, K, r, with_mask=True)
            dst = sx.dest_ids[pos]
            r, dst, from_prior = self._slice_draws(slice_, r, dst, from_prior)
        else:
            dst = sx.dest_ids[self._sample_dests(sx, k2, K, r)]
            r, dst = self._slice_draws(slice_, r, dst)
            from_prior = None
        fields = ["broker", "part", "disk", "topic", "valid", "is_lead",
                  "load_leader", "load_follower"]
        if self.w.pref_leader != 0.0:
            fields.append("pos")
        if plan is not None and self.config.replica_move_cost:
            fields.append("orig_broker")
        rows = self._take_rows(sx, carry, r, tuple(fields))
        src = rows["broker"]
        part = rows["part"]

        # feasibility (reference GoalUtils.legitMove:153 + exclusions)
        offline = ~(st.broker_alive[src] & st.disk_alive[src, rows["disk"]])
        movable = sx.topic_movable[rows["topic"]] | offline
        feasible = rows["valid"] & movable & (src != dst)
        # no second replica of the partition on dst (reference
        # ClusterModel.relocateReplica precondition)
        members = self._take_members(sx, part)  # [K, max_rf]
        member_broker = self._member_field(sx, carry, members, "broker", -1)
        feasible &= ~(member_broker == dst[:, None]).any(axis=1)

        is_lead = rows["is_lead"]
        load = jnp.where(
            is_lead[:, None], rows["load_leader"], rows["load_follower"]
        )  # [K, 4]
        load = jnp.where(rows["valid"][:, None], load, 0.0)

        # destination logdir: most-free alive disk on dst
        ddst_pct = carry.disk_load[dst] / (st.disk_capacity[dst] + 1e-12)
        ddst_pct = jnp.where(st.disk_alive[dst], ddst_pct, jnp.inf)
        d_dst = jnp.argmin(ddst_pct, axis=1).astype(jnp.int32)
        d_src = rows["disk"]

        pot = rows["load_leader"][:, int(Resource.NW_OUT)]
        lbin = jnp.where(is_lead, rows["load_leader"][:, int(Resource.NW_IN)], 0.0)
        dcount = jnp.ones(r.shape, jnp.int32)
        dlcount = is_lead.astype(jnp.int32)

        delta = self._move_delta(
            sx,
            carry,
            g,
            src=src,
            dst=dst,
            dload_src=-load,
            dload_dst=load,
            dcount=dcount,
            dlcount=dlcount,
            dpot=pot,
            dlbin=lbin,
            d_src=d_src,
            d_dst=d_dst,
            ddisk=load[:, int(Resource.DISK)],
        )

        # rack cells (reference RackAwareGoal)
        rack_s, rack_d = st.broker_rack[src], st.broker_rack[dst]
        c_s = self._rack_cell(carry, part, rack_s)
        c_d = self._rack_cell(carry, part, rack_d)
        drack = (_relu(c_s - 2.0) - _relu(c_s - 1.0)) + (_relu(c_d) - _relu(c_d - 1.0))
        delta += self.w.rack * jnp.where(rack_s != rack_d, drack, 0.0) / sx.n_valid

        # topic cells (reference TopicReplicaDistributionGoal)
        if self.w.topic_dist != 0.0:
            t = rows["topic"]
            tt = self.constraint.topic_replica_count_balance_threshold
            upper = jnp.ceil(g["topic_avg"][t] * tt)
            lower = jnp.floor(g["topic_avg"][t] * max(0.0, 2.0 - tt))

            def cell(cnt):
                return _relu(cnt - upper) + _relu(lower - cnt)

            ct_s = carry.broker_topic_count[t, src].astype(jnp.float32)
            ct_d = carry.broker_topic_count[t, dst].astype(jnp.float32)
            dtop = (cell(ct_s - 1.0) - cell(ct_s)) + (cell(ct_d + 1.0) - cell(ct_d))
            delta += self.w.topic_dist * dtop / g["total_count"]

        # offline-replica term (reference OptimizationVerifier BROKEN_BROKERS)
        dst_ok = st.broker_alive[dst] & st.disk_alive[dst, d_dst]
        doff = (~dst_ok).astype(jnp.float32) - offline.astype(jnp.float32)
        delta += self.w.offline * doff / sx.n_valid

        # preferred-leader eligibility shift (reference PreferredLeaderElectionGoal)
        if self.w.pref_leader != 0.0:
            pref = (rows["pos"] == 0) & rows["valid"] & ~is_lead
            was = pref & ~offline
            now = pref & dst_ok
            delta += (
                self.w.pref_leader
                * (now.astype(jnp.float32) - was.astype(jnp.float32))
                / max(1, self.shape.P)
            )

        # movement pricing: cost to stray from the ORIGINAL broker (statics
        # hold the pre-optimization placement), refunded when moving home —
        # keeps the plan executable (reference ExecutionProposal data-to-move)
        if plan is not None and self.config.replica_move_cost:
            orig = rows["orig_broker"]
            stray = (dst != orig).astype(jnp.float32) - (src != orig).astype(jnp.float32)
            delta += plan.replica_cost * stray

        payload = dict(r=r, dst=dst, d_dst=d_dst, load=load, is_lead=is_lead,
                       pot=pot, lbin=lbin, d_src=d_src)
        if from_prior is not None:
            payload["from_prior"] = from_prior
        return delta, feasible, src, dst, part, payload

    def _intra_disk_candidates(
        self, sx, carry: EngineCarry, key: jax.Array, g, plan=None, slice_=None
    ):
        """K_r intra-broker disk-move candidates (JBOD rebalance_disk mode).

        Replicas move between a broker's OWN logdirs — no broker-level load
        shifts, only the intra-broker disk goals + offline term move
        (reference IntraBrokerDiskCapacity/UsageDistributionGoal,
        Executor.intraBrokerMoveReplicas:1036 alterReplicaLogDirs).
        Returned in the replica-candidate payload shape: src == dst broker,
        so `_apply`'s broker-axis scatters cancel and only replica_disk +
        disk_load actually change.
        """
        st = sx.state
        K = self.K_r
        D = self.shape.max_disks_per_broker
        r = self._slice_draws(slice_, self._sample_sources(sx, key, K, plan))
        fields = ["broker", "part", "disk", "topic", "valid", "is_lead",
                  "load_leader", "load_follower"]
        if plan is not None and self.config.replica_move_cost:
            fields.append("orig_disk")
        rows = self._take_rows(sx, carry, r, tuple(fields))
        b = rows["broker"]
        d_src = rows["disk"]
        part = rows["part"]

        # destination logdir: most-free alive disk on b, excluding the
        # current slot
        pct = carry.disk_load[b] / (st.disk_capacity[b] + 1e-12)
        pct = jnp.where(st.disk_alive[b], pct, jnp.inf)
        pct = jnp.where(jax.nn.one_hot(d_src, D, dtype=bool), jnp.inf, pct)
        d_dst = jnp.argmin(pct, axis=1).astype(jnp.int32)

        off_src = ~(st.broker_alive[b] & st.disk_alive[b, d_src])
        movable = sx.topic_movable[rows["topic"]] | off_src
        dst_ok = st.broker_alive[b] & st.disk_alive[b, d_dst]
        feasible = (
            rows["valid"] & movable & dst_ok & (d_dst != d_src)
        )

        is_lead = rows["is_lead"]
        load = jnp.where(
            is_lead[:, None], rows["load_leader"], rows["load_follower"]
        )
        load = jnp.where(rows["valid"][:, None], load, 0.0)
        ddisk = load[:, int(Resource.DISK)]

        # intra-broker disk terms: one broker, one row reshuffled
        row = carry.disk_load[b]
        shift = (
            jax.nn.one_hot(d_dst, D, dtype=jnp.float32)
            - jax.nn.one_hot(d_src, D, dtype=jnp.float32)
        ) * ddisk[:, None]
        bsum = row.sum(-1)
        delta = self._disk_terms(sx, b, row + shift, bsum, g) - self._disk_terms(
            sx, b, row, bsum, g
        )
        # offline-replica shift (rescuing off a failed logdir)
        delta += self.w.offline * (
            (~dst_ok).astype(jnp.float32) - off_src.astype(jnp.float32)
        ) / sx.n_valid
        # movement pricing vs the ORIGINAL logdir (alterReplicaLogDirs copies
        # the whole replica; reference ExecutionProposal data-to-move)
        if plan is not None and self.config.replica_move_cost:
            orig = rows["orig_disk"]
            stray = (d_dst != orig).astype(jnp.float32) - (d_src != orig).astype(
                jnp.float32
            )
            delta += plan.replica_cost * stray

        payload = dict(r=r, dst=b, d_dst=d_dst, load=load, is_lead=is_lead,
                       pot=rows["load_leader"][:, int(Resource.NW_OUT)],
                       lbin=jnp.where(
                           is_lead, rows["load_leader"][:, int(Resource.NW_IN)], 0.0
                       ),
                       d_src=d_src)
        if self.config.diagnostics:
            # intra-broker candidates never draw destinations from the
            # prior; the mask exists so the diagnostics bundle is uniform
            payload["from_prior"] = jnp.zeros(r.shape, bool)
        return delta, feasible, b, b, part, payload

    def _swap_candidates(
        self, sx, carry: EngineCarry, key: jax.Array, g, plan=None, slice_=None
    ):
        """K_s replica-swap candidates: r <-> q exchange brokers (and disk
        slots).  Escapes local optima single relocations cannot leave through
        a feasible intermediate (reference AbstractGoal.maybeApplySwapAction:236,
        ResourceDistributionGoal swap-in/out :502-599; SURVEY §7 hard part (b)).

        Returns (delta, feasible, src, dst, part_r, part_q, payload); the
        surviving swaps are applied as two linked relocation payload rows.
        """
        st = sx.state
        K = self.K_s
        if K == 0:
            z = jnp.zeros((0,), jnp.float32)
            zi = jnp.zeros((0,), jnp.int32)
            zb = jnp.zeros((0,), bool)
            payload = dict(
                r=zi, q=zi, load_r=jnp.zeros((0, NUM_RESOURCES)), load_q=jnp.zeros((0, NUM_RESOURCES)),
                lead_r=zb, lead_q=zb, pot_r=z, pot_q=z, lbin_r=z, lbin_q=z,
                d_r=zi, d_q=zi,
            )
            return z, zb, zi, zi, zi, zi, payload
        k1, k2 = jax.random.split(key)
        r = self._sample_sources(sx, k1, K, plan)
        q = _uniform_idx(k2, (K,), sx.n_source)
        r, q = self._slice_draws(slice_, r, q)
        # ONE row bundle for both draw lanes (gather of a concat == concat
        # of gathers): the model-sharded twin resolves it with a single
        # psum round instead of two
        fields = ["broker", "part", "disk", "topic", "valid", "is_lead",
                  "load_leader", "load_follower"]
        if self.w.pref_leader != 0.0:
            fields.append("pos")
        if plan is not None and self.config.replica_move_cost:
            fields.append("orig_broker")
        n_r = r.shape[0]
        rows = self._take_rows(sx, carry, jnp.concatenate([r, q]), tuple(fields))
        rows_r = {f: a[:n_r] for f, a in rows.items()}
        rows_q = {f: a[n_r:] for f, a in rows.items()}
        src = rows_r["broker"]
        dst = rows_q["broker"]
        part_r = rows_r["part"]
        part_q = rows_q["part"]

        d_r = rows_r["disk"]
        d_q = rows_q["disk"]
        off_r = ~(st.broker_alive[src] & st.disk_alive[src, d_r])
        off_q = ~(st.broker_alive[dst] & st.disk_alive[dst, d_q])
        movable_r = sx.topic_movable[rows_r["topic"]] | off_r
        movable_q = sx.topic_movable[rows_q["topic"]] | off_q
        feasible = (
            rows_r["valid"]
            & rows_q["valid"]
            & movable_r
            & movable_q
            & (src != dst)
            & (part_r != part_q)
            # both ends must be allowed destinations (each receives a replica)
            & sx.dest_ok[src]
            & sx.dest_ok[dst]
            # each replica inherits the other's disk slot — that slot must be
            # alive (relocations argmin over alive disks; swaps must not be
            # the back door onto a failed logdir)
            & st.disk_alive[dst, d_q]
            & st.disk_alive[src, d_r]
        )
        # neither partition may end up duplicated on its new broker
        mem_r = self._take_members(sx, part_r)  # [K, max_rf]
        mem_r_broker = self._member_field(sx, carry, mem_r, "broker", -1)
        feasible &= ~(mem_r_broker == dst[:, None]).any(axis=1)
        mem_q = self._take_members(sx, part_q)
        mem_q_broker = self._member_field(sx, carry, mem_q, "broker", -1)
        feasible &= ~(mem_q_broker == src[:, None]).any(axis=1)

        lead_r = rows_r["is_lead"]
        lead_q = rows_q["is_lead"]
        load_r = jnp.where(
            lead_r[:, None], rows_r["load_leader"], rows_r["load_follower"]
        )
        load_r = jnp.where(rows_r["valid"][:, None], load_r, 0.0)
        load_q = jnp.where(
            lead_q[:, None], rows_q["load_leader"], rows_q["load_follower"]
        )
        load_q = jnp.where(rows_q["valid"][:, None], load_q, 0.0)
        pot_r = rows_r["load_leader"][:, int(Resource.NW_OUT)]
        pot_q = rows_q["load_leader"][:, int(Resource.NW_OUT)]
        lbin_r = jnp.where(lead_r, rows_r["load_leader"][:, int(Resource.NW_IN)], 0.0)
        lbin_q = jnp.where(lead_q, rows_q["load_leader"][:, int(Resource.NW_IN)], 0.0)

        rdisk = int(Resource.DISK)
        # r -> (dst, q's disk slot), q -> (src, r's disk slot)
        delta = self._move_delta(
            sx,
            carry,
            g,
            src=src,
            dst=dst,
            dload_src=load_q - load_r,
            dload_dst=load_r - load_q,
            dcount=jnp.zeros(r.shape, jnp.int32),
            dlcount=lead_r.astype(jnp.int32) - lead_q.astype(jnp.int32),
            dpot=pot_r - pot_q,
            dlbin=lbin_r - lbin_q,
            d_src=d_r,
            d_dst=d_q,
            ddisk=load_r[:, rdisk] - load_q[:, rdisk],
        )

        # rack cells for both partitions (reference RackAwareGoal)
        rack_s, rack_d = st.broker_rack[src], st.broker_rack[dst]

        def rack_delta(part, rack_from, rack_to):
            c_f = self._rack_cell(carry, part, rack_from)
            c_t = self._rack_cell(carry, part, rack_to)
            d = (_relu(c_f - 2.0) - _relu(c_f - 1.0)) + (_relu(c_t) - _relu(c_t - 1.0))
            return jnp.where(rack_from != rack_to, d, 0.0)

        delta += self.w.rack * (
            rack_delta(part_r, rack_s, rack_d) + rack_delta(part_q, rack_d, rack_s)
        ) / sx.n_valid

        # topic cells for both topics (reference TopicReplicaDistributionGoal)
        if self.w.topic_dist != 0.0:
            tt = self.constraint.topic_replica_count_balance_threshold

            def topic_delta(t, b_from, b_to):
                upper = jnp.ceil(g["topic_avg"][t] * tt)
                lower = jnp.floor(g["topic_avg"][t] * max(0.0, 2.0 - tt))

                def cell(cnt):
                    return _relu(cnt - upper) + _relu(lower - cnt)

                ct_f = carry.broker_topic_count[t, b_from].astype(jnp.float32)
                ct_t = carry.broker_topic_count[t, b_to].astype(jnp.float32)
                return (cell(ct_f - 1.0) - cell(ct_f)) + (cell(ct_t + 1.0) - cell(ct_t))

            delta += self.w.topic_dist * (
                topic_delta(rows_r["topic"], src, dst)
                + topic_delta(rows_q["topic"], dst, src)
            ) / g["total_count"]

        # offline-replica shifts for both replicas
        r_ok = st.broker_alive[dst] & st.disk_alive[dst, d_q]
        q_ok = st.broker_alive[src] & st.disk_alive[src, d_r]
        doff = (
            (~r_ok).astype(jnp.float32)
            - off_r.astype(jnp.float32)
            + (~q_ok).astype(jnp.float32)
            - off_q.astype(jnp.float32)
        )
        delta += self.w.offline * doff / sx.n_valid

        # preferred-leader eligibility shifts
        if self.w.pref_leader != 0.0:
            def pref_delta(rows_x, was_off, now_ok, lead):
                pref = (rows_x["pos"] == 0) & rows_x["valid"] & ~lead
                was = pref & ~was_off
                now = pref & now_ok
                return now.astype(jnp.float32) - was.astype(jnp.float32)

            delta += (
                self.w.pref_leader
                * (
                    pref_delta(rows_r, off_r, r_ok, lead_r)
                    + pref_delta(rows_q, off_q, q_ok, lead_q)
                )
                / max(1, self.shape.P)
            )

        # movement pricing for both strays
        if plan is not None and self.config.replica_move_cost:
            orig_r = rows_r["orig_broker"]
            orig_q = rows_q["orig_broker"]
            stray = (
                (dst != orig_r).astype(jnp.float32)
                - (src != orig_r).astype(jnp.float32)
                + (src != orig_q).astype(jnp.float32)
                - (dst != orig_q).astype(jnp.float32)
            )
            delta += plan.replica_cost * stray

        payload = dict(
            r=r, q=q, load_r=load_r, load_q=load_q, lead_r=lead_r, lead_q=lead_q,
            pot_r=pot_r, pot_q=pot_q, lbin_r=lbin_r, lbin_q=lbin_q, d_r=d_r, d_q=d_q,
        )
        return delta, feasible, src, dst, part_r, part_q, payload

    def _leadership_candidates(
        self, sx, carry: EngineCarry, key: jax.Array, g, plan=None, slice_=None
    ):
        """K_l leadership-transfer candidates (reference relocateLeadership:374)."""
        st = sx.state
        K = self.K_l
        R = self.shape.R
        if K == 0:
            z = jnp.zeros((0,), jnp.float32)
            zi = jnp.zeros((0,), jnp.int32)
            zb = jnp.zeros((0,), bool)
            zl = jnp.zeros((0, NUM_RESOURCES), jnp.float32)
            payload = dict(rf=zi, rt=zi, dl_f=zl, dl_t=zl, dlbin_src=z, dlbin_dst=z)
            return z, zb, zi, zi, zi, payload
        rt = self._slice_draws(slice_, _uniform_idx(key, (K,), sx.n_source))
        fields = ["broker", "part", "disk", "valid", "is_lead",
                  "load_leader", "load_follower"]
        if self.w.pref_leader != 0.0:
            fields.append("pos")
        if plan is not None and self.config.leadership_move_cost:
            fields.append("orig_lead")
        rows_t = self._take_rows(sx, carry, rt, tuple(fields))
        part = rows_t["part"]
        members = self._take_members(sx, part)  # [K, max_rf]
        m_idx = jnp.minimum(members, R - 1)
        m_lead = self._member_field(sx, carry, members, "is_lead", False)
        rf = m_idx[jnp.arange(rt.shape[0]), jnp.argmax(m_lead, axis=1)]
        rows_f = self._take_rows(
            sx, carry, rf,
            tuple(f for f in fields if f not in ("part", "valid", "is_lead")),
        )

        src, dst = rows_f["broker"], rows_t["broker"]
        dst_ok = st.broker_alive[dst] & st.disk_alive[dst, rows_t["disk"]]
        feasible = (
            rows_t["valid"]
            & ~rows_t["is_lead"]
            & m_lead.any(axis=1)
            & dst_ok
            & sx.lead_ok[dst]
        )

        # load shift: rf leader->follower on src, rt follower->leader on dst
        dl_f = rows_f["load_follower"] - rows_f["load_leader"]  # [K, 4]
        dl_t = rows_t["load_leader"] - rows_t["load_follower"]
        dlbin = rows_t["load_leader"][:, int(Resource.NW_IN)]  # gained by dst
        # NOTE: src loses rf's leader NW_IN; handled via asymmetric lbin deltas
        delta = self._move_delta(
            sx,
            carry,
            g,
            src=src,
            dst=dst,
            dload_src=dl_f,
            dload_dst=dl_t,
            dcount=jnp.zeros(rt.shape, jnp.int32),
            dlcount=jnp.ones(rt.shape, jnp.int32),
            dpot=jnp.zeros(rt.shape, jnp.float32),
            dlbin_src=rows_f["load_leader"][:, int(Resource.NW_IN)],
            dlbin=dlbin,
            d_src=rows_f["disk"],
            d_dst=rows_t["disk"],
            ddisk_src=dl_f[:, int(Resource.DISK)],
            ddisk=dl_t[:, int(Resource.DISK)],
        )

        if self.w.pref_leader != 0.0:
            src_ok = st.broker_alive[src] & st.disk_alive[src, rows_f["disk"]]
            pref_f = (rows_f["pos"] == 0) & src_ok  # rf becomes violating
            pref_t = (rows_t["pos"] == 0) & dst_ok  # rt stops violating
            delta += (
                self.w.pref_leader
                * (pref_f.astype(jnp.float32) - pref_t.astype(jnp.float32))
                / max(1, self.shape.P)
            )

        # movement pricing: a transfer whose new leader is not the partition's
        # ORIGINAL leader pays; restoring the original leader refunds
        # (the executor applies each as a preferred-leader election batch,
        # reference executor/Executor.java:1091)
        if plan is not None and self.config.leadership_move_cost:
            stray = (~rows_t["orig_lead"]).astype(jnp.float32) - (
                ~rows_f["orig_lead"]
            ).astype(jnp.float32)
            delta += plan.lead_cost * stray

        payload = dict(rf=rf, rt=rt, dl_f=dl_f, dl_t=dl_t,
                       dlbin_src=rows_f["load_leader"][:, int(Resource.NW_IN)],
                       dlbin_dst=dlbin)
        return delta, feasible, src, dst, part, payload

    def _move_delta(
        self,
        sx,
        carry,
        g,
        *,
        src,
        dst,
        dload_src,
        dload_dst,
        dcount,
        dlcount,
        dpot,
        dlbin,
        d_src,
        d_dst,
        ddisk,
        dlbin_src=None,
        ddisk_src=None,
    ):
        """Objective delta for candidates touching brokers (src, dst).

        dload_src is ADDED to src (callers pass negative values to remove
        load); dload_dst is added to dst.  dcount/dlcount/dpot/dlbin move
        from src to dst unless an asymmetric *_src override is given.
        """
        st = sx.state
        if dlbin_src is None:
            dlbin_src = dlbin
        if ddisk_src is None:
            ddisk_src = ddisk

        def gather(b):
            return (
                carry.broker_load[b],
                carry.broker_replica_count[b],
                carry.broker_leader_count[b],
                carry.broker_potential_nw_out[b],
                carry.broker_leader_bytes_in[b],
            )

        ls, rs, lcs, ps, lbs = gather(src)
        ld, rd, lcd, pd, lbd = gather(dst)
        # ONE stacked _broker_terms call over a [4, K] lane axis
        # (src-old, dst-old, src-new, dst-new) instead of four separate
        # inlines: element-wise identical math, but the traced step program
        # shrinks by ~1.5k equations — warm-start trace time is paced by
        # graph size (this helper is reached from all three candidate kinds)
        b4 = jnp.stack([src, dst, src, dst])
        t4 = self._broker_terms(
            sx,
            b4,
            jnp.stack([ls, ld, ls + dload_src, ld + dload_dst]),
            jnp.stack([rs, rd, rs - dcount, rd + dcount]),
            jnp.stack([lcs, lcd, lcs - dlcount, lcd + dlcount]),
            jnp.stack([ps, pd, ps - dpot, pd + dpot]),
            jnp.stack([lbs, lbd, lbs - dlbin_src, lbd + dlbin]),
            g,
        )
        delta = (t4[2] + t4[3]) - (t4[0] + t4[1])

        # host-granularity capacity (same-host moves cancel)
        h_s, h_d = st.broker_host[src], st.broker_host[dst]
        hl_s, hl_d = carry.host_load[h_s], carry.host_load[h_d]
        th4 = self._host_terms(
            sx,
            jnp.stack([h_s, h_s, h_d, h_d]),
            jnp.stack([hl_s + dload_src, hl_s, hl_d + dload_dst, hl_d]),
        )
        dh = th4[0] - th4[1] + th4[2] - th4[3]
        delta += jnp.where(h_s != h_d, dh, 0.0)

        # intra-broker disk goals
        if self.w.intra_cap != 0.0 or self.w.intra_dist != 0.0:
            row_s, row_d = carry.disk_load[src], carry.disk_load[dst]
            D = self.shape.max_disks_per_broker
            oh_s = jax.nn.one_hot(d_src, D, dtype=jnp.float32)
            oh_d = jax.nn.one_hot(d_dst, D, dtype=jnp.float32)
            row_s2 = row_s - oh_s * ddisk_src[:, None]
            row_d2 = row_d + oh_d * ddisk[:, None]
            bsum_s, bsum_d = row_s.sum(-1), row_d.sum(-1)
            td4 = self._disk_terms(
                sx,
                jnp.stack([src, src, dst, dst]),
                jnp.stack([row_s2, row_s, row_d2, row_d]),
                jnp.stack([bsum_s - ddisk_src, bsum_s, bsum_d + ddisk, bsum_d]),
                g,
            )
            delta += td4[0] - td4[1] + td4[2] - td4[3]

        # dispersion tiebreaker via sufficient statistics
        cap_s = st.broker_capacity[src] + 1e-12
        cap_d = st.broker_capacity[dst] + 1e-12
        p_s, p_d = ls / cap_s, ld / cap_d
        p_s2, p_d2 = (ls + dload_src) / cap_s, (ld + dload_dst) / cap_d
        a_s = sx.alive[src][:, None].astype(jnp.float32)
        a_d = sx.alive[dst][:, None].astype(jnp.float32)
        dsum = a_s * (p_s2 - p_s) + a_d * (p_d2 - p_d)
        dsumsq = a_s * (p_s2**2 - p_s**2) + a_d * (p_d2**2 - p_d**2)
        delta += self._tie_term(
            sx, g["pct_sum"] + dsum, g["pct_sumsq"] + dsumsq
        ) - self._tie_term(sx, g["pct_sum"], g["pct_sumsq"])
        return delta

    # ------------------------------------------------------------------
    # step: propose -> evaluate -> select -> apply
    # ------------------------------------------------------------------

    def _propose_kinds(
        self, sx: EngineStatics, carry: EngineCarry, k_r, k_s, k_l, g,
        plan=None, slice_=None,
    ):
        """Raw per-kind candidate bundles (replica/intra, swap, leadership).

        With `slice_` (the mesh engine's candidate-axis sharding) each
        bundle covers only this shard's contiguous slice of the full-K
        stream; the mesh step all_gathers the bundles back into full-K
        order before `_assemble_prop` — the candidate COLUMNS are the only
        thing that ever crosses shards."""
        repl = (
            self._intra_disk_candidates
            if self.config.intra_broker
            else self._replica_candidates
        )
        return (
            repl(sx, carry, k_r, g, plan, slice_=slice_),
            self._swap_candidates(sx, carry, k_s, g, plan, slice_=slice_),
            self._leadership_candidates(sx, carry, k_l, g, plan, slice_=slice_),
        )

    def _propose(self, sx: EngineStatics, carry: EngineCarry, k_r, k_s, k_l, g, plan=None):
        """Sample + evaluate all candidate kinds; return a selection/apply
        bundle.  Payloads carry src broker / topic / partition explicitly so
        `_apply` never has to index replica-axis arrays for them — which lets
        the mesh engine (parallel/mesh.py) assemble rows evaluated on OTHER
        devices' candidate shards without touching their replica arrays.
        """
        return self._assemble_prop(
            sx, carry, *self._propose_kinds(sx, carry, k_r, k_s, k_l, g, plan)
        )

    def _assemble_prop(self, sx: EngineStatics, carry: EngineCarry, raw_r, raw_s, raw_l):
        """Concatenate per-kind bundles into the selection/apply bundle
        (shared verbatim by the plain step and the mesh step's post-gather
        path, so the two can never diverge)."""
        R1 = self.shape.R - 1
        dr, fr, sr, tr, pr, payr = raw_r
        ds, fs, ss, ts, ps1, ps2, pays = raw_s
        dl, fl, sl, tl, pl, payl = raw_l
        # diagnostics rider: the replica rows' took-the-prior-branch mask
        # (never part of the apply payload — swaps/leads are not prior-drawn)
        payr = dict(payr)
        from_prior = payr.pop("from_prior", None)

        delta = jnp.concatenate([dr, ds, dl])
        feas = jnp.concatenate([fr, fs, fl])
        src = jnp.concatenate([sr, ss, sl])
        dst = jnp.concatenate([tr, ts, tl])
        # two partition lanes: swaps touch two partitions; other kinds
        # duplicate their single partition (harmless)
        part1 = jnp.concatenate([pr, ps1, pl])
        part2 = jnp.concatenate([pr, ps2, pl])

        # a surviving swap applies as two linked relocations: r -> (dst, q's
        # disk) and q -> (src, r's disk) — the scatter path is shared
        r_ext = jnp.concatenate([payr["r"], pays["r"], pays["q"]])
        payr_ext = dict(
            r=r_ext,
            src=jnp.concatenate([sr, ss, ts]),
            dst=jnp.concatenate([payr["dst"], ts, ss]),
            d_dst=jnp.concatenate([payr["d_dst"], pays["d_q"], pays["d_r"]]),
            load=jnp.concatenate([payr["load"], pays["load_r"], pays["load_q"]]),
            is_lead=jnp.concatenate([payr["is_lead"], pays["lead_r"], pays["lead_q"]]),
            pot=jnp.concatenate([payr["pot"], pays["pot_r"], pays["pot_q"]]),
            lbin=jnp.concatenate([payr["lbin"], pays["lbin_r"], pays["lbin_q"]]),
            d_src=jnp.concatenate([payr["d_src"], pays["d_r"], pays["d_q"]]),
            topic=self._take_rows(
                sx, carry, jnp.minimum(r_ext, R1), ("topic",)
            )["topic"],
            part=jnp.concatenate([pr, ps1, ps2]),
        )
        # rf/rt disk lookups bundled into ONE row fetch (single psum round
        # on the sharded twin)
        n_f = payl["rf"].shape[0]
        d_ft = self._take_rows(
            sx, carry,
            jnp.minimum(jnp.concatenate([payl["rf"], payl["rt"]]), R1),
            ("disk",),
        )["disk"]
        payl_ext = dict(
            payl,
            src_b=sl,
            dst_b=tl,
            d_f=d_ft[:n_f],
            d_t=d_ft[n_f:],
        )
        out = dict(
            delta=delta, feas=feas, src=src, dst=dst, part1=part1, part2=part2,
            nr=dr.shape[0], ns=ds.shape[0], payr=payr_ext, payl=payl_ext,
        )
        if from_prior is not None:
            out["from_prior"] = from_prior
        return out

    def _select(self, accept, delta, src, dst, part1, part2, num_parts=None):
        """Conflict resolution: unique ranks; a candidate survives iff it is
        the best-ranked touching each of its brokers and its partition(s).
        `num_parts` overrides the partition-segment count (the sharded engine
        selects over GLOBAL partition ids spanning all shards)."""
        B = self.shape.B
        P = self.shape.P if num_parts is None else num_parts
        big = jnp.where(accept, delta, jnp.inf)
        rank = jnp.argsort(jnp.argsort(big)).astype(jnp.int32)
        seg = jnp.concatenate([src, dst, B + part1, B + part2])
        ranks4 = jnp.concatenate([rank, rank, rank, rank])
        min_rank = jax.ops.segment_min(ranks4, seg, num_segments=B + P)
        return (
            accept
            & (min_rank[src] == rank)
            & (min_rank[dst] == rank)
            & (min_rank[B + part1] == rank)
            & (min_rank[B + part2] == rank)
        )

    def _step(self, sx: EngineStatics, carry: EngineCarry, temperature, plan=None):
        key, k_r, k_s, k_l, k_u = jax.random.split(carry.key, 5)
        g = self._globals(sx, carry)
        prop = self._propose(sx, carry, k_r, k_s, k_l, g, plan)
        return self._accept_select_apply(sx, carry, prop, temperature, key, k_u)

    def _accept_select_apply(
        self, sx: EngineStatics, carry: EngineCarry, prop, temperature, key, k_u
    ):
        """Metropolis acceptance + conflict resolution + scatter, from a
        full-K proposal bundle.  Shared by the plain step and the mesh
        step (which only replaces how `prop` was produced), so acceptance
        semantics cannot diverge between the two."""
        delta, feas = prop["delta"], prop["feas"]

        # Metropolis acceptance: delta < -T log u  (greedy at T=0)
        u = jax.random.uniform(k_u, (delta.shape[0],), minval=1e-12, maxval=1.0)
        thresh = -temperature * jnp.log(u)
        accept = feas & (delta < thresh - 1e-12)

        survive = self._select(
            accept, delta, prop["src"], prop["dst"], prop["part1"], prop["part2"]
        )
        nr, ns = prop["nr"], prop["ns"]
        sv_r = survive[:nr]
        sv_s = survive[nr: nr + ns]
        sv_l = survive[nr + ns:]
        sv_r_ext = jnp.concatenate([sv_r, sv_s, sv_s])

        carry = self._apply(sx, carry, sv_r_ext, prop["payr"], sv_l, prop["payl"])
        carry = dataclasses.replace(carry, key=key)
        stats = dict(
            accepted=survive.sum(),
            improving=(feas & (delta < 0)).sum(),
            delta=jnp.where(survive, delta, 0.0).sum(),
        )
        if self.config.diagnostics:
            # per-kind acceptance + prior-draw usage: read-only reductions
            # of the already-computed survival masks (the ledger's
            # per-round acceptance-by-kind trajectory)
            fp = prop.get("from_prior")
            if fp is None:
                fp = jnp.zeros((nr,), bool)
            stats.update(
                acc_replica=sv_r.sum(),
                acc_swap=sv_s.sum(),
                acc_lead=sv_l.sum(),
                prior_cands=fp.sum(),
                prior_acc=(sv_r & fp).sum(),
            )
        return carry, stats

    def _apply(
        self, sx, carry: EngineCarry, sv_r, payr, sv_l, payl,
        *, r_offset=None, p_offset=None, r_size=None, p_size=None,
    ) -> EngineCarry:
        """Scatter surviving candidates into placement + aggregates.

        Payload rows identify everything by explicit fields (replica id, src
        broker, topic, partition) rather than replica-array lookups.  When
        `r_offset`/`p_offset` are given (sharded engine), replica/partition
        ids are GLOBAL: aggregates (replicated broker/host/topic axes) absorb
        every row, while placement scatters translate to shard-local indices
        and rows owned by other shards fall out of range and are dropped.
        """
        st = sx.state
        B, R, D = self.shape.B, self.shape.R, self.shape.max_disks_per_broker
        # local extents of the placement arrays: the sharded engine passes
        # its per-shard row counts so ownership bounds and drop sentinels
        # track the LOCAL arrays, not the global shape
        r_size = R if r_size is None else r_size
        p_size = self.shape.P if p_size is None else p_size
        drop = dict(mode="drop")
        # ownership masks: negative indices would WRAP (python semantics), so
        # rows owned by other shards must be masked to the sentinel explicitly
        if r_offset is None:
            r_ids, own_r = payr["r"], True
        else:
            r_ids = payr["r"] - r_offset
            own_r = (r_ids >= 0) & (r_ids < r_size)
        if p_offset is None:
            p_ids, own_p = payr["part"], True
        else:
            p_ids = payr["part"] - p_offset
            own_p = (p_ids >= 0) & (p_ids < p_size)

        # ---- replica moves ----
        r = jnp.where(sv_r & own_r, r_ids, r_size)
        dst = payr["dst"]
        load = payr["load"] * sv_r[:, None]
        src = payr["src"]
        src_idx = jnp.where(sv_r, src, B)
        dst_idx = jnp.where(sv_r, dst, B)

        replica_broker = carry.replica_broker.at[r].set(dst, **drop)
        replica_disk = carry.replica_disk.at[r].set(payr["d_dst"], **drop)

        bl = carry.broker_load.at[src_idx].add(-load, **drop).at[dst_idx].add(load, **drop)
        ones = sv_r.astype(jnp.int32)
        rc = carry.broker_replica_count.at[src_idx].add(-ones, **drop).at[dst_idx].add(
            ones, **drop
        )
        dlc = (sv_r & payr["is_lead"]).astype(jnp.int32)
        lc = carry.broker_leader_count.at[src_idx].add(-dlc, **drop).at[dst_idx].add(dlc, **drop)
        dpot = payr["pot"] * sv_r
        pot = carry.broker_potential_nw_out.at[src_idx].add(-dpot, **drop).at[dst_idx].add(
            dpot, **drop
        )
        dlb = payr["lbin"] * sv_r
        lb = carry.broker_leader_bytes_in.at[src_idx].add(-dlb, **drop).at[dst_idx].add(
            dlb, **drop
        )
        t = payr["topic"]
        T = self.shape.num_topics
        tc = (
            carry.broker_topic_count.at[jnp.where(sv_r, t, T), src_idx].add(-ones, **drop)
            .at[jnp.where(sv_r, t, T), dst_idx].add(ones, **drop)
        )
        p = jnp.where(sv_r & own_p, p_ids, p_size)
        rack_s = st.broker_rack[src]
        rack_d = st.broker_rack[dst]
        prc = (
            carry.part_rack_count.at[p, rack_s].add(-ones, **drop)
            .at[p, rack_d].add(ones, **drop)
        )
        ddisk = load[:, int(Resource.DISK)]
        dl_ = (
            carry.disk_load.at[src_idx, payr["d_src"]].add(-ddisk, **drop)
            .at[dst_idx, payr["d_dst"]].add(ddisk, **drop)
        )
        h_s = st.broker_host[src]
        h_d = st.broker_host[dst]
        H = self.shape.num_hosts
        hl = (
            carry.host_load.at[jnp.where(sv_r, h_s, H)].add(-load, **drop)
            .at[jnp.where(sv_r, h_d, H)].add(load, **drop)
        )

        # ---- leadership transfers ----
        if r_offset is None:
            rf_ids, rt_ids, own_f, own_t = payl["rf"], payl["rt"], True, True
        else:
            rf_ids = payl["rf"] - r_offset
            rt_ids = payl["rt"] - r_offset
            own_f = (rf_ids >= 0) & (rf_ids < r_size)
            own_t = (rt_ids >= 0) & (rt_ids < r_size)
        rf = jnp.where(sv_l & own_f, rf_ids, r_size)
        rt = jnp.where(sv_l & own_t, rt_ids, r_size)
        is_leader = carry.replica_is_leader.at[rf].set(False, **drop).at[rt].set(True, **drop)

        src_l = payl["src_b"]
        dst_l = payl["dst_b"]
        sl_idx = jnp.where(sv_l, src_l, B)
        tl_idx = jnp.where(sv_l, dst_l, B)
        dl_f = payl["dl_f"] * sv_l[:, None]
        dl_t = payl["dl_t"] * sv_l[:, None]
        bl = bl.at[sl_idx].add(dl_f, **drop).at[tl_idx].add(dl_t, **drop)
        ones_l = sv_l.astype(jnp.int32)
        lc = lc.at[sl_idx].add(-ones_l, **drop).at[tl_idx].add(ones_l, **drop)
        lb = (
            lb.at[sl_idx].add(-payl["dlbin_src"] * sv_l, **drop)
            .at[tl_idx].add(payl["dlbin_dst"] * sv_l, **drop)
        )
        d_f = payl["d_f"]
        d_t = payl["d_t"]
        dl_ = (
            dl_.at[sl_idx, d_f].add(dl_f[:, int(Resource.DISK)], **drop)
            .at[tl_idx, d_t].add(dl_t[:, int(Resource.DISK)], **drop)
        )
        h_f = st.broker_host[src_l]
        h_t = st.broker_host[dst_l]
        hl = (
            hl.at[jnp.where(sv_l, h_f, H)].add(dl_f, **drop)
            .at[jnp.where(sv_l, h_t, H)].add(dl_t, **drop)
        )

        return dataclasses.replace(
            carry,
            replica_broker=replica_broker,
            replica_is_leader=is_leader,
            replica_disk=replica_disk,
            broker_load=bl,
            broker_replica_count=rc,
            broker_leader_count=lc,
            broker_potential_nw_out=pot,
            broker_leader_bytes_in=lb,
            broker_topic_count=tc,
            part_rack_count=prc,
            disk_load=dl_,
            host_load=hl,
        )

    def _round_prep_impl(self, sx: EngineStatics, carry: EngineCarry):
        """Between-rounds bookkeeping as ONE program: refresh aggregates
        (wash float drift), build the next round's sampling plan, and read
        the cheap early-stop signal.  Separately jitted these three share
        the O(R) aggregate rebuild and O(B) globals/objective work and cost
        three dispatch+sync round trips; fused they cost one."""
        carry = self._refresh_impl(sx, carry)
        plan = self._plan_impl(sx, carry)
        cheap = self._cheap_violations_impl(sx, carry)
        return carry, plan, cheap

    def _scan_impl(
        self, sx: EngineStatics, carry: EngineCarry, temps: jax.Array, plan=None
    ):
        def body(c, t):
            return self._step(sx, c, t, plan)

        return jax.lax.scan(body, carry, temps)

    def _make_scan(self):
        """(statics, carry, temps, plan=None) -> (carry, stats); for external
        composition (portfolio sharding, graft entry)."""
        return self._scan_impl

    # ------------------------------------------------------------------
    # fused whole-anneal program (scan over rounds, rounds scan over steps)
    # ------------------------------------------------------------------

    def _run_fused_impl(self, sx: EngineStatics, carry: EngineCarry):
        return self._fused_rounds_body(sx, carry, verbose=False)

    def _run_fused_verbose_impl(self, sx: EngineStatics, carry: EngineCarry):
        return self._fused_rounds_body(sx, carry, verbose=True)

    def _fused_rounds_body(
        self, sx: EngineStatics, carry: EngineCarry, *, verbose: bool
    ):
        """The entire multi-round anneal as ONE program.

        `lax.scan` over `num_rounds + extra_round_budget` rounds; each
        round body is the existing per-round step scan plus the
        between-rounds program (`_round_prep_impl`: aggregate refresh,
        sampling-plan rebuild, cheap early-stop signal).  The temperature
        schedule, the authoritative full-goal-chain early stop, and the
        extra-polish-rounds loop run in-graph as cond-masked rounds: once
        the `done` flag sets, the remaining round bodies are cheap no-ops.

        Semantics match the legacy host loop exactly — same round budgets,
        same bounded full-chain check count, same RNG chain — with one
        re-phrasing: the early-stop checks run at the TOP of each round
        against the previous round's post-refresh carry, which is the same
        decision the legacy loop takes at the BOTTOM of the previous round
        (the host rebuilds the legacy history shape from the per-round
        flags this returns).

        Returns (final carry, per-round scalars): `accepted`, `ran`,
        `stopped` (early stop fired before this round), `temperature`,
        `cheap`, and — in the verbose variant — the full-chain `objective`.
        Only these O(rounds) scalars are ever fetched eagerly; the carry
        stays on device for the result report to consume.
        """
        cfg = self.config
        total = cfg.num_rounds + cfg.extra_round_budget
        t0, plan0 = self._schedule_init(sx, carry)

        def round_body(st, rnd):
            return self._fused_round_step(sx, st, rnd, verbose=verbose)

        init = (
            carry, plan0, jnp.float32(jnp.inf), jnp.bool_(False),
            jnp.int32(FULL_CHECK_BUDGET), jnp.float32(jnp.inf), jnp.bool_(False),
            t0,
        )
        (carry, *_), ys = jax.lax.scan(round_body, init, jnp.arange(total))
        return carry, ys

    def _schedule_init(self, sx: EngineStatics, carry: EngineCarry):
        """(t0, plan0) of a fresh anneal: the initial temperature scale and
        round-0 sampling plan.  Shared by the whole-anneal fused program
        (inlined) and the segmented runner's init program (standalone) —
        the same traced subprograms, so both paths see identical values
        (the legacy loop already computes them standalone; fused-vs-legacy
        parity is pinned by tests)."""
        obj0, _ = self._eval_impl(sx, carry)
        return obj0 * self.config.init_temperature_scale, self._plan_impl(sx, carry)

    def _fused_round_step(self, sx: EngineStatics, st, rnd, *, verbose: bool):
        """ONE round of the fused schedule — the scan body shared verbatim
        by the whole-anneal program and the segmented slice programs, so
        a segmented run is byte-identical to the unsegmented one by
        construction.  `st` carries (carry, plan, cheap_prev, done,
        checks_left, prev_v, has_prev, t0); `rnd` is the ABSOLUTE round
        index (a slice scans base+arange(L)); rounds past the schedule
        (`rnd >= total` — a slice overhanging the end) are cond-masked
        no-ops exactly like post-early-stop rounds."""
        cfg = self.config
        n_main = cfg.num_rounds
        total = n_main + cfg.extra_round_budget
        tol_on = cfg.early_stop_violations >= 0.0
        tol = jnp.float32(cfg.early_stop_tol)
        carry, plan, cheap_prev, done, checks_left, prev_v, has_prev, t0 = st
        in_range = rnd < total
        active = ~done & in_range
        is_extra = rnd >= n_main
        main_stop = jnp.bool_(False)
        run = active
        if tol_on:
            # main-round gate: the previous round's cheap O(B) signal
            # opens the bounded authoritative check (legacy
            # full_checks_left semantics); extra-round gate: the
            # full-chain violation decides continue/stop every round
            main_gate = (
                active & ~is_extra & (rnd > 0)
                & (checks_left > 0) & (cheap_prev <= tol)
            )
            extra_gate = active & is_extra
            need_full = main_gate | extra_gate
            full_v = jax.lax.cond(
                need_full,
                lambda: self._eval_impl(sx, carry)[1],
                lambda: jnp.float32(jnp.inf),
            )
            main_stop = main_gate & (full_v <= tol)
            checks_left = jnp.where(
                main_gate & ~main_stop, checks_left - 1, checks_left
            )
            extra_stop = extra_gate & (
                (full_v <= tol) | (has_prev & (full_v > prev_v * 0.9))
            )
            stop = main_stop | extra_stop
            done = done | stop
            run = active & ~stop
            prev_v = jnp.where(run & is_extra, full_v, prev_v)
            has_prev = has_prev | (run & is_extra)

        t_r = jnp.where(
            is_extra | (rnd == n_main - 1),
            jnp.float32(0.0),
            t0 * cfg.temperature_decay ** rnd.astype(jnp.float32),
        ).astype(jnp.float32)

        diag = self.config.diagnostics
        stat_keys = (
            ("accepted", "acc_replica", "acc_swap", "acc_lead",
             "prior_cands", "prior_acc")
            if diag
            else ("accepted",)
        )

        def do_round(carry, plan):
            temps = jnp.full((cfg.steps_per_round,), t_r, jnp.float32)
            carry, stats = self._scan_impl(sx, carry, temps, plan)
            carry, plan, cheap = self._round_prep_impl(sx, carry)
            return carry, plan, cheap, {k: stats[k].sum() for k in stat_keys}

        carry, plan, cheap_prev, acc = jax.lax.cond(
            run,
            do_round,
            lambda c, p: (
                c, p, jnp.float32(jnp.inf),
                {k: jnp.int32(0) for k in stat_keys},
            ),
            carry,
            plan,
        )
        # `stopped` marks only the MAIN early stop: the legacy history
        # flags early_stop on the round whose post-refresh state
        # satisfied the full chain, never on an extra-round exit
        ys = dict(
            accepted=acc["accepted"], ran=run, stopped=main_stop,
            temperature=t_r, cheap=cheap_prev,
        )
        if diag:
            # round-boundary goal quality: the full-chain objective + the
            # per-goal violation vector of the post-round carry, masked to
            # NaN on not-ran rounds.  A read of the carry only — the scan
            # state and every RNG stream are untouched, so placements stay
            # byte-identical to the diagnostics-off program.
            n_goals = len(self.chain.goals)
            obj_d, viol_d = jax.lax.cond(
                run,
                lambda: self._eval_vec_impl(sx, carry),
                lambda: (
                    jnp.float32(jnp.nan),
                    jnp.full((n_goals,), jnp.nan, jnp.float32),
                ),
            )
            ys.update(
                objective=obj_d, goal_viol=viol_d,
                acc_replica=acc["acc_replica"], acc_swap=acc["acc_swap"],
                acc_lead=acc["acc_lead"], prior_cands=acc["prior_cands"],
                prior_acc=acc["prior_acc"],
            )
        assert set(ys) == set(self._ys_keys()), (
            "fused ys keys drifted from FUSED_YS_KEYS/FUSED_DIAG_YS_KEYS — "
            "update both, or AOT artifacts unflatten the wrong structure"
        )
        if verbose and "objective" not in ys:
            ys["objective"] = jax.lax.cond(
                run,
                lambda: self._eval_impl(sx, carry)[0],
                lambda: jnp.float32(jnp.nan),
            )
        return (
            carry, plan, cheap_prev, done, checks_left, prev_v, has_prev, t0
        ), ys

    # ------------------------------------------------------------------
    # segmented (preemptible) fused execution — fleet/scheduler.py
    # ------------------------------------------------------------------

    def _seg_init_impl(self, sx: EngineStatics, carry: EngineCarry):
        """Round-0 scan state of the fused schedule as ONE standalone
        program (the segmented runner's prelude): exactly the init the
        whole-anneal program builds in-graph."""
        t0, plan0 = self._schedule_init(sx, carry)
        return (
            plan0, jnp.float32(jnp.inf), jnp.bool_(False),
            jnp.int32(FULL_CHECK_BUDGET), jnp.float32(jnp.inf),
            jnp.bool_(False), t0,
        )

    def _seg_slice_impl(self, L: int, sx, carry, seg, base):
        """Rounds [base, base+L) of the fused schedule: the SAME round
        body as the whole-anneal scan, over a slice of the round indices,
        with the full scan state (carry + plan + early-stop flags + t0)
        carried in and out — splitting a scan into consecutive sub-scans
        of the same body is composition, not approximation.  carry and
        seg are donated: HBM holds one placement copy across slices like
        the unsegmented run."""

        def round_body(st, rnd):
            return self._fused_round_step(sx, st, rnd, verbose=False)

        (carry, *seg), ys = jax.lax.scan(
            round_body, (carry, *seg), base + jnp.arange(L)
        )
        return carry, tuple(seg), ys

    def _seg_fn(self, L: int):
        fn = self._seg_fns.get(L)
        if fn is None:
            fn = jax.jit(partial(self._seg_slice_impl, L), donate_argnums=(1, 2))
            self._seg_fns[L] = fn
        return fn

    def _run_segmented(self, seg_ctx: SegmentContext, *, initial_placement=None):
        """The fused anneal in wall-bounded preemptible slices.

        The fused program cannot be interrupted mid-XLA-execution, so the
        device scheduler's bounded-wall preemption needs the schedule cut
        into separately dispatched slices: run `L` rounds, block until the
        device is actually idle, call `seg_ctx.checkpoint()` (the
        scheduler pauses us here while an URGENT request runs), repeat.
        `L` adapts to `seg_ctx.slice_budget_s` from a measured per-round
        wall EWMA, in powers of two (<= SEGMENT_MAX_ROUNDS) so at most
        log2 distinct slice programs compile per engine.

        Byte parity with the unsegmented run holds by construction: every
        slice scans the SAME `_fused_round_step` body over consecutive
        absolute round indices with the full scan state carried across
        dispatches on device (slices overhanging the schedule are masked
        no-op rounds), and the warm-start path rides the same
        `init_carry_from` copy-in — pinned by tests/test_scheduler.py.
        The cost of preemptibility is one blocking sync per slice instead
        of one per run (reported in the timing record)."""
        cfg = self.config
        sx = self.statics
        t_start = time.monotonic()
        # the slice programs are plain jits outside the AOT tier — their
        # first segmented run traces fresh, and cold-start accounting
        # must say so (once per engine, like the unsegmented path)
        self._record_fused_trace("fresh")
        carry = self._init_for_run(initial_placement)
        if self._jit_seg_init is None:
            self._jit_seg_init = jax.jit(self._seg_init_impl)
        seg = self._jit_seg_init(sx, carry)
        total = cfg.num_rounds + cfg.extra_round_budget
        budget = max(1e-3, float(seg_ctx.slice_budget_s))
        ys_parts: list[dict] = []
        base = 0
        device_s = 0.0
        round_wall = None
        L = 1
        while base < total:
            # a slice length's FIRST dispatch pays the slice program's
            # trace+compile — that wall must not feed the per-round
            # estimate, or every growth step re-inflates the EWMA and
            # collapses the next length back toward 1 (extra syncs for
            # nothing); the very first slice has no other estimate, so
            # its (polluted, conservative) sample is kept and later
            # steady-state slices wash it out
            first_use = L not in self._seg_fns
            t0s = time.monotonic()
            # black-box spool: one Begin per slice DISPATCH, closed only
            # after the blocking sync below — a hang inside the slice
            # program (or a kill mid-slice) leaves "slice K, rounds
            # [base, base+L) in flight" on disk, the exact trail the
            # multichip post-mortem needs (common/blackbox.py)
            _bb = _BLACKBOX
            bb_seq = _bb.begin(
                "engine-slice",
                slice=len(ys_parts), base_round=int(base), rounds=int(L),
                total_rounds=int(total),
            ) if _bb.enabled else 0
            try:
                count_dispatch("engine.slice")
                carry, seg, ys = self._seg_fn(L)(
                    sx, carry, seg, jnp.asarray(base, jnp.int32)
                )
                # the slice boundary IS a blocking sync: the device must
                # be genuinely idle before the scheduler may hand it to
                # an urgent request (seg[2] is the in-graph `done` flag)
                count_dispatch("engine.sync")
                ys_host, done = jax.device_get((ys, seg[2]))
            except BaseException as e:  # noqa: BLE001 — recorded, re-raised
                _bb.end(bb_seq, ok=False, error=repr(e))
                raise
            _bb.end(bb_seq, done=bool(done))
            wall = time.monotonic() - t0s
            device_s += wall
            ys_parts.append(ys_host)
            base += L
            per_round = wall / L
            if round_wall is None:
                round_wall = per_round
            elif not first_use:
                round_wall = 0.5 * round_wall + 0.5 * per_round
            if bool(done) or base >= total:
                break
            L = 1
            while L * 2 * round_wall <= budget and L * 2 <= SEGMENT_MAX_ROUNDS:
                L *= 2
            if seg_ctx.checkpoint is not None:
                seg_ctx.checkpoint()
            # fault-tolerance carry snapshot: the device is idle (the
            # sync above) and carry/seg are not yet donated into the
            # next slice, so the host copy races nothing.  A no-op
            # single predicate when tpu.mesh.ft.checkpoint.every.slices
            # is 0.
            def _capture(base=base, carry=carry, seg=seg, parts=ys_parts):
                count_dispatch("engine.snapshot")
                return CarryCheckpoint(
                    base=int(base),
                    carry=snapshot_host_tree(carry),
                    seg=snapshot_host_tree(seg),
                    ys_parts=[dict(p) for p in parts],
                    n_chains=1,
                )

            seg_ctx.offer_snapshot(_capture)
        ys = {
            k: np.concatenate([p[k] for p in ys_parts]) for k in self._ys_keys()
        }
        history = self._fused_history(ys, verbose=False)
        timing = dict(
            timing=True, fused=True, segmented=True,
            segments=len(ys_parts), blocking_syncs=len(ys_parts),
            device_s=round(device_s, 6),
            host_dispatch_s=round(time.monotonic() - t_start - device_s, 6),
        )
        conv = self._convergence_summary(ys)
        if conv is not None:
            timing["convergence"] = conv
        history.append(timing)
        return self.carry_to_state(carry), history

    # ------------------------------------------------------------------
    # driver
    # ------------------------------------------------------------------

    @device_op("engine.run")
    def run(self, *, verbose: bool = False, initial_placement=None):
        """Execute the annealing schedule; returns (final_state, history).

        history is a list of per-round dicts (round, temperature, accepted,
        optional early_stop/extra/objective) plus ONE timing record
        (`timing=True`) carrying the device/host split and the number of
        blocking host<->device syncs the optimization performed — the
        fused path's contract is O(1) syncs regardless of round count.

        `initial_placement` (optional (replica_broker, replica_is_leader,
        replica_disk) triple of this shape) warm-starts the anneal from a
        prior accepted placement instead of the statics' current one —
        the streaming controller's incremental re-anneal.  The RNG chain,
        schedule, and early-stop semantics are unchanged; only the round-0
        carry differs.

        With an ambient SegmentContext (the device scheduler granted this
        dispatch preemptibly — fleet/scheduler.py) the fused schedule runs
        as wall-bounded slices with a preemption checkpoint between them;
        results are byte-identical to the unsegmented run (see
        `_run_segmented`).  Verbose runs stay unsegmented: they are
        debugging tools, and the per-round eval would have to ride every
        slice program.
        """
        if self.config.fused_rounds:
            seg_ctx = current_segment_context()
            if seg_ctx is not None and not verbose:
                return self._run_segmented(
                    seg_ctx, initial_placement=initial_placement
                )
            return self._run_fused(
                verbose=verbose, initial_placement=initial_placement
            )
        return self._run_legacy(
            verbose=verbose, initial_placement=initial_placement
        )

    def _init_for_run(self, initial_placement):
        key = jax.random.PRNGKey(self.config.seed)
        count_dispatch("engine.init")
        if initial_placement is None:
            return self.init_carry(key)
        return self.init_carry_from(key, initial_placement)

    def _fused_history(self, ys, *, verbose: bool) -> list[dict]:
        """Per-round history records from the fused program's fetched ys
        — one builder for the whole-anneal and segmented runners, so the
        two report identically (a segmented run may have fetched fewer
        trailing not-ran rows; those contribute no records anyway).
        With convergence diagnostics compiled in, each record additionally
        carries the round-boundary objective, the per-goal violation
        vector, acceptance counts by move kind, and prior-draw usage."""
        diag = self.config.diagnostics
        history: list[dict] = []
        for r in range(len(ys["ran"])):
            if ys["stopped"][r] and history:
                history[-1]["early_stop"] = True
            if not ys["ran"][r]:
                continue
            rec = dict(
                round=len(history),
                temperature=float(ys["temperature"][r]),
                accepted=int(ys["accepted"][r]),
            )
            if r >= self.config.num_rounds:
                rec["extra"] = True
            if diag:
                rec["objective"] = float(ys["objective"][r])
                rec["goal_violations"] = [
                    round(float(v), 8) for v in np.asarray(ys["goal_viol"][r])
                ]
                rec["accepted_by_kind"] = {
                    "replica": int(ys["acc_replica"][r]),
                    "swap": int(ys["acc_swap"][r]),
                    "leadership": int(ys["acc_lead"][r]),
                }
                rec["prior"] = {
                    "candidates": int(ys["prior_cands"][r]),
                    "accepted": int(ys["prior_acc"][r]),
                }
            elif verbose:
                rec["objective"] = float(ys["objective"][r])
            history.append(rec)
        return history

    def _convergence_summary(self, ys) -> dict | None:
        """Compact convergence summary from one run's fetched per-round
        ys (None unless diagnostics are compiled in) — attached to the
        run's timing record, threaded into the analyzer.optimize span and
        the decision ledger (analyzer/ledger.py)."""
        if not self.config.diagnostics:
            return None
        ran = np.asarray(ys["ran"]).astype(bool)
        obj = np.asarray(ys["objective"])
        viol = np.asarray(ys["goal_viol"])
        last = int(np.nonzero(ran)[0][-1]) if ran.any() else None
        return dict(
            rounds=int(ran.sum()),
            early_stop=bool(np.asarray(ys["stopped"]).any()),
            objective_trajectory=[round(float(x), 8) for x in obj[ran]],
            temperatures=[float(x) for x in np.asarray(ys["temperature"])[ran]],
            accepted=[int(x) for x in np.asarray(ys["accepted"])[ran]],
            accepted_by_kind=dict(
                replica=int(np.asarray(ys["acc_replica"])[ran].sum()),
                swap=int(np.asarray(ys["acc_swap"])[ran].sum()),
                leadership=int(np.asarray(ys["acc_lead"])[ran].sum()),
            ),
            prior=dict(
                candidates=int(np.asarray(ys["prior_cands"])[ran].sum()),
                accepted=int(np.asarray(ys["prior_acc"])[ran].sum()),
            ),
            goal_names=self.chain.names(),
            final_goal_violations=(
                [round(float(v), 8) for v in viol[last]]
                if last is not None
                else []
            ),
        )

    def _run_fused(self, *, verbose: bool = False, initial_placement=None):
        sx = self.statics
        t_start = time.monotonic()
        carry = self._init_for_run(initial_placement)
        if verbose:
            if self._jit_run_fused_verbose is None:
                self._jit_run_fused_verbose = jax.jit(
                    self._run_fused_verbose_impl, donate_argnums=(1,)
                )
            fused = self._jit_run_fused_verbose
        else:
            fused = self._fn("_jit_run_fused")
            if not isinstance(fused, _WarmedFn):
                # no warm pool ran for this engine: the call below traces
                # the fused program lazily — a fresh trace the cold-start
                # report must see
                self._record_fused_trace("fresh")
        count_dispatch("engine.run")
        carry, ys = fused(sx, carry)
        t_disp = time.monotonic()
        # the run's ONE blocking sync: O(rounds) scalars (completes only
        # when the whole fused program has); the final carry stays on
        # device for the report/proposal-diff programs to consume.
        # Timing-split caveat: with ASYNC dispatch (TPU) host_dispatch_s is
        # host-side trace/dispatch work and device_s is device search time;
        # on a synchronous backend (CPU) the fused call above executes the
        # program inline, so device compute lands in host_dispatch_s and
        # device_s measures only this drain — compare wall clocks, not the
        # split, on CPU.
        count_dispatch("engine.sync")
        ys = jax.device_get(ys)
        t_sync = time.monotonic()

        history = self._fused_history(ys, verbose=verbose)
        timing = dict(
            timing=True, fused=True, blocking_syncs=1,
            host_dispatch_s=round(t_disp - t_start, 6),
            device_s=round(t_sync - t_disp, 6),
        )
        conv = self._convergence_summary(ys)
        if conv is not None:
            timing["convergence"] = conv
        history.append(timing)
        return self.carry_to_state(carry), history

    # ------------------------------------------------------------------
    # fused streaming-cycle program (delta scatter + re-anneal + extract)
    # ------------------------------------------------------------------

    def _cycle_statics(self) -> EngineStatics:
        """Statics variant safe to pass alongside DONATED live load arrays.

        The cycle program donates the live replica_load_leader/follower
        buffers; if the statics' embedded state still held the same Array
        objects, XLA would see a donated buffer aliased by a second input
        (an error).  The cycle statics therefore carry zero-filled
        placeholder load leaves — `_cycle_impl` overwrites them with the
        donated (and freshly scattered) arrays before anything reads
        loads.  Cached per statics generation; the placeholder zeros are
        reused across rebinds (same shape every generation)."""
        cached = self._cycle_sx
        if cached is not None and cached[0] is self.statics:
            return cached[1]
        zeros = (
            cached[2]
            if cached is not None
            else jnp.zeros((self.shape.R, NUM_RESOURCES), jnp.float32)
        )
        sxc = dataclasses.replace(
            self.statics,
            state=dataclasses.replace(
                self.statics.state,
                replica_load_leader=zeros,
                replica_load_follower=zeros,
            ),
        )
        self._cycle_sx = (self.statics, sxc, zeros)
        return sxc

    def _cycle_impl(self, sx, ll, fl, rows, new_ll, new_fl, rb, il, dk):
        """The steady-state streaming cycle as ONE XLA program: delta
        scatter + before-report + warm re-anneal + after-report + device
        validation + the proposal-extraction payload.

        Inlines exactly the programs the staged path dispatches separately
        (LiveState's scatter, optimizer's `_report`, `init_carry_from`,
        the fused anneal, `validate_on_device`), sharing their traced
        subprograms — so with full-K config the resulting placement is
        byte-identical to the staged path by construction (pinned by
        tests/test_controller.py).  `ll`/`fl` are DONATED; the scattered
        arrays come back as outputs, making the caller (LiveState) the
        sole owner of one live load copy at 500k-replica scale.

        Reports run in full f32 regardless of `score_dtype` — they are
        user-facing numbers matching optimizer._report, not search
        internals."""
        drop = dict(mode="drop")
        ll = ll.at[rows].set(new_ll, **drop)
        fl = fl.at[rows].set(new_fl, **drop)
        st = dataclasses.replace(
            sx.state, replica_load_leader=ll, replica_load_follower=fl
        )
        sx = dataclasses.replace(sx, state=st)
        agg_b = compute_aggregates(st)
        obj_b, viol_b, _ = self.chain.evaluate(
            st, agg=agg_b, constraint=self.constraint
        )
        stats_b = compute_stats(st, agg_b)
        key = jax.random.PRNGKey(self.config.seed)
        carry = self._init_from_impl(sx, key, rb, il, dk)
        carry, ys = self._fused_rounds_body(sx, carry, verbose=False)
        final = self.carry_to_state(carry, sx)
        agg_a = compute_aggregates(final)
        obj_a, viol_a, _ = self.chain.evaluate(
            final, agg=agg_a, constraint=self.constraint
        )
        stats_a = compute_stats(final, agg_a)
        payload = dict(
            ys=ys,
            obj_before=obj_b, viol_before=viol_b, stats_before=stats_b,
            obj_after=obj_a, viol_after=viol_a, stats_after=stats_a,
            replica_broker=carry.replica_broker,
            replica_is_leader=carry.replica_is_leader,
            replica_disk=carry.replica_disk,
            replica_offline=final.replica_offline,
            replica_disk_bytes=ll[:, int(Resource.DISK)],
            checks=validate_on_device(final),
        )
        return ll, fl, payload

    @device_op("engine.cycle")
    def run_cycle(self, ll, fl, rows, new_ll, new_fl, initial_placement):
        """Host driver for `_cycle_impl`: ONE dispatch, ONE blocking fetch.

        `ll`/`fl` are the LIVE f32[R, 4] load arrays (donated — the caller
        must adopt the returned pair as the new live arrays); `rows` /
        `new_ll` / `new_fl` are the window delta, `initial_placement` the
        warm-start (rb, il, dk) triple.  Rows are padded to power-of-two
        buckets with the out-of-range sentinel R (dropped by the scatter)
        so successive windows of different delta sizes reuse one compiled
        cycle program — same bucketing as LiveState's standalone scatter.

        Returns (new_ll, new_fl, payload, history): payload is the fetched
        host dict (reports, final placement, checks, disk bytes), history
        the same per-round record list `run()` produces.  No copies of
        `initial_placement` are needed: the cycle program does not donate
        rb/il/dk, unlike the standalone fused run."""
        R = self.shape.R
        n = int(len(rows))
        width = max(64, 1 << (max(n, 1) - 1).bit_length())
        pad = width - n
        rows = np.concatenate(
            [np.asarray(rows, np.int32), np.full(pad, R, np.int32)]
        )
        pad_z = np.zeros((pad, NUM_RESOURCES), np.float32)
        new_ll = np.concatenate([np.asarray(new_ll, np.float32), pad_z])
        new_fl = np.concatenate([np.asarray(new_fl, np.float32), pad_z])
        rb, il, dk = initial_placement
        sxc = self._cycle_statics()
        t_start = time.monotonic()
        count_dispatch("engine.cycle")
        out_ll, out_fl, payload = self._jit_run_cycle(
            sxc, ll, fl,
            jnp.asarray(rows), jnp.asarray(new_ll), jnp.asarray(new_fl),
            jnp.asarray(rb, jnp.int32), jnp.asarray(il, bool),
            jnp.asarray(dk, jnp.int32),
        )
        t_disp = time.monotonic()
        # the cycle's ONE blocking sync: reports + placement + per-round ys
        count_dispatch("engine.extract")
        host = jax.device_get(payload)
        t_sync = time.monotonic()
        history = self._fused_history(host["ys"], verbose=False)
        timing = dict(
            timing=True, fused=True, fused_cycle=True, blocking_syncs=1,
            scatter_width=width,
            host_dispatch_s=round(t_disp - t_start, 6),
            device_s=round(t_sync - t_disp, 6),
        )
        conv = self._convergence_summary(host["ys"])
        if conv is not None:
            timing["convergence"] = conv
        history.append(timing)
        return out_ll, out_fl, host, history

    def _run_legacy(self, *, verbose: bool = False, initial_placement=None):
        """Legacy Python round loop: one scan dispatch + one blocking sync
        per round.  Kept behind `fused_rounds=False` for parity testing and
        per-round host-side debugging.  Convergence diagnostics are a
        fused-path feature (they ride the fused program's per-round ys);
        the legacy loop ignores `OptimizerConfig.diagnostics` — per-round
        inspection here is what `verbose=True` is for."""
        cfg = self.config
        sx = self.statics
        t_start = time.monotonic()
        sync = dict(n=0, s=0.0)

        def fetch(x):
            """device_get with the blocking wait metered (timing record)."""
            t0 = time.monotonic()
            count_dispatch("engine.sync")
            v = jax.device_get(x)
            sync["n"] += 1
            sync["s"] += time.monotonic() - t0
            return v

        carry = self._init_for_run(initial_placement)

        t0_obj = float(fetch(self._fn("_jit_eval")(sx, carry)[0]))
        t0_obj *= cfg.init_temperature_scale
        plan = self._fn("_jit_plan")(sx, carry)
        history = []
        # the authoritative (full-chain) early-stop check is bounded: when
        # the cheap gate opens but goals folded into candidate deltas (topic
        # dist) still have work, re-checking every round would cost more
        # than it saves
        full_checks_left = FULL_CHECK_BUDGET
        # f32-quantized threshold: must take the SAME branch the fused
        # in-graph compare would (OptimizerConfig.early_stop_tol)
        tol = cfg.early_stop_tol

        def _temp(rnd: int) -> float:
            if rnd == cfg.num_rounds - 1:
                return 0.0
            return t0_obj * (cfg.temperature_decay**rnd)

        # pipelined round loop: round rnd+1's scan is DISPATCHED before
        # round rnd's cheap signal is fetched, so the device keeps
        # annealing through the host's per-round network round trip
        # (tunneled TPU).  When the early stop fires, one speculative
        # round's device work is abandoned — early stops are rare at the
        # scales where a round is expensive, and the stop still returns
        # the pre-speculation state.
        temps0 = jnp.full((cfg.steps_per_round,), _temp(0), jnp.float32)
        next_carry, next_stats = self._fn("_scan")(sx, carry, temps0, plan)
        for rnd in range(cfg.num_rounds):
            stats = next_stats
            # fused between-rounds program: wash float drift out of the
            # aggregates, plan the next round's sampling, read the cheap
            # early-stop signal — one dispatch instead of three
            carry, plan, cheap = self._fn("_jit_round_prep")(sx, next_carry)
            if rnd + 1 < cfg.num_rounds:
                temps = jnp.full(
                    (cfg.steps_per_round,), _temp(rnd + 1), jnp.float32
                )
                next_carry, next_stats = self._fn("_scan")(sx, carry, temps, plan)
            # ONE device round-trip per round: cheap (control flow) and the
            # per-step accept counts ride the same fetch — each extra
            # device_get is a full network round trip
            cheap, step_accepts = fetch((cheap, stats["accepted"]))
            accepted = int(step_accepts.sum())
            history.append(dict(round=rnd, temperature=_temp(rnd), accepted=accepted))
            if verbose:
                history[-1]["objective"] = float(
                    fetch(self._fn("_jit_eval")(sx, carry)[0])
                )
            # early stop: all goals already satisfied.  The O(B) lower bound
            # gates the authoritative full-chain check so healthy rounds pay
            # ~nothing.
            if (
                cfg.early_stop_violations >= 0.0
                and rnd < cfg.num_rounds - 1
                and full_checks_left > 0
                and float(cheap) <= tol
            ):
                if float(fetch(self._fn("_jit_eval")(sx, carry)[1])) <= tol:
                    history[-1]["early_stop"] = True
                    break
                full_checks_left -= 1
        else:
            # schedule exhausted with goals possibly unsatisfied (bad starts:
            # mass decommission) — polish with extra greedy rounds while the
            # full chain reports violations and they keep shrinking
            prev_v = None
            for _ in range(cfg.extra_round_budget):
                v = float(fetch(self._fn("_jit_eval")(sx, carry)[1]))
                if v <= tol or (
                    prev_v is not None
                    and v > float(np.float32(prev_v) * np.float32(0.9))
                ):
                    break
                prev_v = v
                temps = jnp.zeros((cfg.steps_per_round,), jnp.float32)
                carry, stats = self._fn("_scan")(sx, carry, temps, plan)
                carry, plan, _cheap = self._fn("_jit_round_prep")(sx, carry)
                history.append(dict(
                    round=len(history), temperature=0.0, extra=True,
                    accepted=int(fetch(stats["accepted"]).sum()),
                ))
                if verbose:
                    # same record schema as the fused path's verbose extras
                    history[-1]["objective"] = float(
                        fetch(self._fn("_jit_eval")(sx, carry)[0])
                    )
        history.append(dict(
            timing=True, fused=False, blocking_syncs=sync["n"],
            device_s=round(sync["s"], 6),
            host_s=round(time.monotonic() - t_start - sync["s"], 6),
        ))
        return self.carry_to_state(carry), history
