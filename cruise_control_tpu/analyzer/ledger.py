"""Durable decision ledger — the proposal→outcome→calibration corpus.

ROADMAP item 3's learned-policy flywheel needs labeled data: (decision
features, search trajectory, realized outcome) triples.  Today nothing
durable records WHY the engine chose a plan or whether the cluster
actually improved after executing it — spans evict from ring buffers,
`OptimizerResult.history` dies with the process, and the executor
journal archives record task transitions, not goal quality.  The ledger
is that corpus as a first-class observability layer, and — as a side
effect — the operator's "explain this rebalance / did it help" surface
(`GET /explain`, `cccli explain`).

Storage: an append-only JSONL file (crash semantics shared with
executor/journal.py — torn tails are repaired before appending and end
replay; every append is flushed+fsync'd, which is cheap at
decision rate).  Fleet deployments namespace one ledger per cluster
under the journal dir.  Record stream:

  {"t": "decision", "id", "ms", "trace_id", "source", ...}   one per
      published proposal: model generation, bucket + config fingerprint,
      work class, per-goal pre/post scores, predicted post-move
      per-broker load summary, per-move features, convergence summary
  {"t": "outcome", "id", "ms", ...}       joined at execution completion
      (duration, completed/aborted/dead, fenced aborts, reaper actions)
  {"t": "calibration", "id", "ms", ...}   predicted vs measured per-goal
      scores and per-broker load prediction error, after the executed
      moves land and the next complete metric window rolls

Rotation/retention (like the executor journal): once the live file holds
`rotate_records` decisions it rotates into a terminal archive
(`<path>.<ms>.<id>.done`) — but NEVER while any decision in it still
awaits its outcome (an execution in flight); `prune_archives`
(config `analyzer.ledger.retention.{count,hours}`) deletes archives
beyond the bounds and skips any archive holding a pending-outcome
decision.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid as uuid_mod

import numpy as np

log = logging.getLogger(__name__)

#: live-file decision count past which record_decision rotates the file
#: into a terminal archive (pending-outcome decisions block rotation)
DEFAULT_ROTATE_RECORDS = 256


class DecisionLedger:
    """Append-only, crash-tolerant JSONL store of decision → outcome →
    calibration records.  Thread-safe: the proposal path, the executor's
    finish hook, and the calibration loop append concurrently."""

    def __init__(self, path: str, *, retention_count: int | None = None,
                 retention_hours: float | None = None,
                 rotate_records: int = DEFAULT_ROTATE_RECORDS,
                 sensors=None, clock=None):
        self.path = os.path.abspath(os.path.expanduser(path))
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.retention_count = retention_count
        self.retention_hours = retention_hours
        self.rotate_records = max(1, int(rotate_records))
        self.sensors = sensors
        self._clock = clock or (lambda: int(time.time() * 1000))
        self._lock = threading.Lock()
        self._file = None
        #: decision ids whose execution is in flight (begin_outcome called,
        #: record_outcome not yet) — rotation and pruning must never strand
        #: or destroy the half-written episode
        self._pending: set[str] = set()
        #: decision ids present in the LIVE file (rebuilt from replay on
        #: first open; bounds the rotation decision)
        self._live_ids: set[str] = set()
        self._scanned = False
        self.records_written = 0
        self.write_errors = 0

    # ------------------------------------------------------------- write

    def _ensure_open_locked(self):
        if self._file is None:
            self._repair_torn_tail()
            if not self._scanned:
                # rebuild the live-file decision id set once per process —
                # rotation bookkeeping must survive restarts
                self._live_ids = {
                    r["id"] for r in self._replay_file(self.path)
                    if r.get("t") == "decision" and r.get("id")
                }
                self._scanned = True
            self._file = open(self.path, "a", encoding="utf-8")  # noqa: SIM115

    def _repair_torn_tail(self):
        """Truncate back to the last fully-valid record before appending:
        gluing a new record onto a crash-torn partial line would poison
        every record after it (executor/journal.py semantics)."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except OSError:
            return
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break
            s = line.strip()
            if s:
                try:
                    rec = json.loads(s)
                except ValueError:
                    break
                if not isinstance(rec, dict) or "t" not in rec:
                    break
            good += len(line)
        if good < len(data):
            with open(self.path, "rb+") as f:
                f.truncate(good)

    def _append_locked(self, record: dict) -> None:
        self._ensure_open_locked()
        self._file.write(json.dumps(record, separators=(",", ":")) + "\n")
        self._file.flush()
        os.fsync(self._file.fileno())
        self.records_written += 1

    def _append(self, record: dict, counter: str) -> bool:
        try:
            with self._lock:
                self._append_locked(record)
        except OSError:
            self.write_errors += 1
            if self.sensors is not None:
                self.sensors.counter("analyzer.ledger.write-errors").inc()
            log.warning("decision-ledger append failed", exc_info=True)
            return False
        if self.sensors is not None:
            self.sensors.counter(counter).inc()
        return True

    def record_decision(self, decision: dict) -> str:
        """Append one `decision` record; returns its ledger id (minted
        here unless the caller supplied one).  May rotate a full live
        file into a terminal archive first — never while a decision in
        it still awaits its outcome."""
        did = decision.get("id") or uuid_mod.uuid4().hex[:16]
        self._maybe_rotate()
        rec = dict(decision, t="decision", id=did)
        rec.setdefault("ms", self._clock())
        if self._append(rec, "analyzer.ledger.decisions"):
            with self._lock:
                self._live_ids.add(did)
        return did

    def begin_outcome(self, decision_id: str) -> None:
        """Mark a decision's execution as in flight: until record_outcome
        lands, the file holding it will neither rotate nor be pruned."""
        with self._lock:
            self._pending.add(decision_id)

    def record_outcome(self, decision_id: str, outcome: dict) -> None:
        rec = dict(outcome, t="outcome", id=decision_id)
        rec.setdefault("ms", self._clock())
        self._append(rec, "analyzer.ledger.outcomes")
        with self._lock:
            self._pending.discard(decision_id)

    def record_calibration(self, decision_id: str, calibration: dict) -> None:
        rec = dict(calibration, t="calibration", id=decision_id)
        rec.setdefault("ms", self._clock())
        self._append(rec, "analyzer.ledger.calibrations")

    def pending_outcomes(self) -> set[str]:
        with self._lock:
            return set(self._pending)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    # --------------------------------------------------- rotation/retention

    def _maybe_rotate(self) -> None:
        """Rotate the live file into a terminal archive once it holds
        `rotate_records` decisions — unless any of them still awaits its
        outcome (the episode must stay joinable in one file)."""
        with self._lock:
            if len(self._live_ids) < self.rotate_records:
                return
            if self._pending & self._live_ids:
                return  # an execution is in flight: never strand its join
            if self._file is not None:
                self._file.close()
                self._file = None
            archive = (
                f"{self.path}.{self._clock()}.{uuid_mod.uuid4().hex[:8]}.done"
            )
            try:
                os.replace(self.path, archive)
            except OSError:
                return  # rotation is best-effort; appends continue
            self._live_ids = set()
        try:
            self.prune_archives()
        except OSError:
            pass

    def _archives(self) -> list[tuple[float, str]]:
        d = os.path.dirname(self.path)
        base = os.path.basename(self.path) + "."
        out: list[tuple[float, str]] = []
        try:
            names = os.listdir(d)
        except OSError:
            return out
        for fn in names:
            if fn.startswith(base) and fn.endswith(".done"):
                p = os.path.join(d, fn)
                try:
                    out.append((os.path.getmtime(p), p))
                except OSError:
                    continue
        out.sort(reverse=True)  # newest first
        return out

    def prune_archives(self, *, now_ms: int | None = None) -> int:
        """Delete ledger archives beyond
        `analyzer.ledger.retention.{count,hours}`.  An archive holding a
        decision whose outcome is still pending is NEVER pruned — the
        in-flight episode's features must survive until its outcome (and
        calibration) can be joined."""
        if self.retention_count is None and self.retention_hours is None:
            return 0
        archives = self._archives()
        doomed: set[str] = set()
        if self.retention_count is not None:
            doomed.update(p for _m, p in archives[max(0, self.retention_count):])
        if self.retention_hours is not None:
            now_s = (now_ms / 1000.0) if now_ms is not None else time.time()
            cutoff = now_s - self.retention_hours * 3600.0
            doomed.update(p for m, p in archives if m < cutoff)
        pending = self.pending_outcomes()
        pruned = 0
        for p in doomed:
            if pending:
                ids = {
                    r.get("id") for r in self._replay_file(p)
                    if r.get("t") == "decision"
                }
                if ids & pending:
                    continue  # a pending episode lives here: sacrosanct
            try:
                os.remove(p)
                pruned += 1
            except OSError:
                pass
        return pruned

    # -------------------------------------------------------------- read

    @staticmethod
    def _replay_file(path: str) -> list[dict]:
        """Decode one ledger file, tolerating crash truncation: a torn
        final line (or garbage after it) ends the replay; everything
        before it is trusted."""
        records: list[dict] = []
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        break
                    if not isinstance(rec, dict) or "t" not in rec:
                        break
                    records.append(rec)
        except OSError:
            return []
        return records

    def replay(self) -> list[dict]:
        """All records, oldest archive first then the live file."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
        out: list[dict] = []
        for _m, p in reversed(self._archives()):
            out.extend(self._replay_file(p))
        out.extend(self._replay_file(self.path))
        return out

    def _join_newest_first(self, stop):
        """Walk the ledger newest file first (live file, then archives
        newest→oldest), yielding joined episodes in newest-decision-first
        order; `stop(episodes)` short-circuits the walk so a /ledger page
        or an /explain lookup never parses 32 archives it does not need.
        Joins are safe under early termination: outcome/calibration
        records can only live in the SAME file as their decision or a
        NEWER one (append-only time order), so by the time a decision is
        seen its joins have already been collected."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
        joins: dict[str, dict] = {}
        episodes: list[dict] = []
        files = [self.path] + [p for _m, p in self._archives()]
        for path in files:
            # records within a file are oldest-first; walking them in
            # REVERSE means every outcome/calibration is collected before
            # its decision is reached (joins never trail their decision in
            # a newer position), and decisions emerge newest-first
            for rec in reversed(self._replay_file(path)):
                did = rec.get("id")
                if not did:
                    continue
                t = rec.get("t")
                if t == "decision":
                    entry = {"decision": rec, "outcome": None,
                             "calibration": None}
                    entry.update(joins.pop(did, {}))
                    episodes.append(entry)
                elif t in ("outcome", "calibration"):
                    joins.setdefault(did, {})[t] = rec
            if stop(episodes):
                break
        return episodes

    def entries(self, *, limit: int = 50) -> list[dict]:
        """Joined episodes, newest decision first:
        {"decision": ..., "outcome": ...|None, "calibration": ...|None}."""
        limit = max(0, int(limit))
        episodes = self._join_newest_first(lambda eps: len(eps) >= limit)
        return episodes[:limit]

    def find(self, *, decision_id: str | None = None,
             trace_id: str | None = None) -> dict | None:
        """The joined episode matching a ledger decision id or a
        flight-recorder trace id; None when nothing matches."""

        def match(entry) -> bool:
            d = entry["decision"]
            if decision_id is not None and d.get("id") == decision_id:
                return True
            return bool(trace_id) and d.get("trace_id") == trace_id

        episodes = self._join_newest_first(
            lambda eps: any(match(e) for e in eps)
        )
        for entry in episodes:
            if match(entry):
                return entry
        return None

    def state_json(self) -> dict:
        with self._lock:
            pending = len(self._pending)
            live = len(self._live_ids)
        return {
            "path": self.path,
            "recordsWritten": self.records_written,
            "writeErrors": self.write_errors,
            "liveDecisions": live,
            "pendingOutcomes": pending,
            "archives": len(self._archives()),
        }


# ----------------------------------------------------------------------
# decision-record construction (shared by the facade and the bench)
# ----------------------------------------------------------------------


def _f(x, nd: int = 6):
    return round(float(x), nd)


def load_summary(stats) -> dict:
    """Compact per-broker load summary from a models/stats.ClusterStats:
    per-resource mean/max/min/std utilization over alive brokers — the
    decision record's PREDICTED post-move load, and the calibration
    record's measured twin."""
    from cruise_control_tpu.common.resources import Resource

    names = [Resource(i).name for i in range(4)]
    out: dict = {}
    for field in ("avg", "max", "min", "std"):
        row = np.asarray(getattr(stats, field), np.float64)
        out[field] = {n: _f(v) for n, v in zip(names, row)}
    return out


def load_summary_error(predicted: dict, measured: dict) -> dict:
    """Per-broker load prediction error between two load_summary dicts:
    absolute error per (statistic, resource) + the headline max absolute
    error over the mean-utilization row (the calibration gauge)."""
    out: dict = {}
    worst = 0.0
    for field in ("avg", "max", "std"):
        p, m = predicted.get(field), measured.get(field)
        if not isinstance(p, dict) or not isinstance(m, dict):
            continue
        row = {
            k: _f(abs(float(m[k]) - float(p[k])))
            for k in p
            if k in m
        }
        out[field] = row
        if field == "avg" and row:
            worst = max(row.values())
    out["maxAbsAvgError"] = _f(worst)
    return out


def _move_rows(proposals, top: int):
    """The `top` highest-data proposal rows without materializing the
    whole set (ProposalSet stays columnar)."""
    n = len(proposals)
    if n == 0:
        return []
    if hasattr(proposals, "top_by_data"):
        return proposals.top_by_data(min(top, n))
    rows = sorted(
        list(proposals), key=lambda p: -p.inter_broker_data_to_move
    )
    return rows[: min(top, n)]


def move_features(result, *, prior_table=None, top: int = 20) -> list[dict]:
    """Per-move feature rows of the decision record: topic, source/dest
    brokers, data to move, leadership change, rack change, and the
    learned prior's contribution to the chosen destinations — the
    featurization ROADMAP item 3's trained policy consumes.  Bounded to
    the `top` moves by data so a 100k-move plan stays a record, not a
    dump."""
    before = result.state_before
    racks = np.asarray(before.broker_rack)
    weights = None
    if prior_table is not None:
        w = getattr(prior_table, "weights", None)
        if w is not None:
            weights = np.asarray(w, np.float64)
    out = []
    for p in _move_rows(result.proposals, top):
        old, new = set(p.old_replicas), set(p.new_replicas)
        added = sorted(new - old)
        removed = sorted(old - new)
        row = {
            "partition": int(p.partition),
            "topic": int(p.topic),
            "sources": [int(b) for b in removed],
            "destinations": [int(b) for b in added],
            "dataMB": _f(p.inter_broker_data_to_move, 3),
            "leadershipChange": bool(p.old_leader != p.new_leader),
            "rackChange": bool(
                {int(racks[b]) for b in added if b < racks.size}
                != {int(racks[b]) for b in removed if b < racks.size}
            ),
        }
        if weights is not None and added:
            t = int(p.topic)
            if 0 <= t < weights.shape[0]:
                row["priorWeight"] = _f(
                    sum(
                        float(weights[t, b])
                        for b in added
                        if 0 <= b < weights.shape[1]
                    )
                )
        out.append(row)
    return out


def build_decision_record(
    result,
    *,
    source: str,
    trace_id: str = "",
    cluster_id: str = "",
    generation=None,
    work_class: str = "",
    config_fingerprint: str = "",
    prior_table=None,
    calibration_eligible: bool = True,
    top_moves: int = 20,
) -> dict:
    """One `decision` record from an OptimizerResult — everything the
    flywheel (and /explain) needs to know about WHY this plan was chosen:
    identity (trace id, generation, bucket, config fingerprint, work
    class), per-goal pre/post scores, the predicted post-move per-broker
    load summary, per-move features, and the engine's convergence
    summary (OptimizerConfig.diagnostics)."""
    timing = next((h for h in result.history if h.get("timing")), {})
    gen = None
    if generation is not None:
        gen = {
            "metadata": int(getattr(generation, "metadata_generation", -1)),
            "load": int(getattr(generation, "load_generation", -1)),
        }
    rec = {
        "trace_id": trace_id,
        "cluster": cluster_id,
        "source": source,
        "workClass": work_class,
        "generation": gen,
        "bucket": timing.get("bucket"),
        "configFingerprint": config_fingerprint,
        "degraded": bool(result.degraded),
        "goals": {
            "names": list(result.goal_names),
            "violationsBefore": [
                _f(v) for v in np.asarray(result.violations_before)
            ],
            "violationsAfter": [
                _f(v) for v in np.asarray(result.violations_after)
            ],
            "objectiveBefore": _f(result.objective_before),
            "objectiveAfter": _f(result.objective_after),
            "balancednessBefore": _f(result.balancedness_before, 3),
            "balancednessAfter": _f(result.balancedness_after, 3),
        },
        "predictedLoad": load_summary(result.stats_after),
        "numReplicaMovements": result.num_inter_broker_moves,
        "numLeaderMovements": result.num_leadership_moves,
        "dataToMoveMB": _f(result.data_to_move, 3),
        "moves": move_features(result, prior_table=prior_table, top=top_moves),
        "convergence": timing.get("convergence"),
        "wallSeconds": _f(result.wall_seconds),
        "calibrationEligible": bool(calibration_eligible),
    }
    return rec
